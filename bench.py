"""Benchmark: Llama pretraining MFU (headline) + conv-model workloads.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...,
"secondary": {...}}.

Workloads (all on whatever device jax exposes — the real TPU chip under
the driver; CPU otherwise with scaled-down shapes):

1. **Llama pretrain step** (headline): fully-compiled TrainStep
   (forward+loss+backward+AdamW), bf16, per-layer remat, memory-pressured
   1.1B-param config.  Model-FLOPs accounting (north star: >=40% MFU):
   flops/token = 6*N_matmul + 6*L*seq*hidden (embedding gather excluded,
   lm_head and causal fwd+bwd attention included).
   vs_baseline = mfu / 0.40.
2. **ResNet-50 train step** (secondary, BASELINE.json config 1 class):
   b128 224x224 bf16 Momentum step — images/s and conv MFU.  FLOPs from
   the lowered jaxpr (utils/flops.py), train = 3x forward.  The measured
   roofline bar is 0.30: BN/elementwise HBM traffic (~19 GB/step at a
   measured ~660 GB/s) bounds the step at ~0.31 even with convs at the
   microbenched 130+ TF/s (see BASELINE.md).
3. **OCR rec forward** (secondary, BASELINE.json config 4 class): CRNN
   (PP-OCR rec architecture) batch inference images/s.

Timing: steps run INSIDE one compiled call (``TrainStep.run_steps`` —
``lax.scan`` over the step body), and each workload is timed differentially
(t_large - t_small over the step delta) so constant dispatch/fetch latency
of the axon tunnel cancels.  A device->host fetch of the loss is the only
true sync on axon (block_until_ready only acks the enqueue).

A matmul microbenchmark validates the nominal peak-FLOPs constant against
silicon, and the lowered StableHLO is scanned for tpu_custom_call to prove
the Pallas kernels (flash attention, rms norm, rope) are in the hot loop.
"""

import gc
import json
import time

import numpy as np


def _measure_matmul_peak(jnp, jax):
    """Time a large bf16 matmul chain to sanity-check the peak-FLOPs
    constant.  One jit call with the loop inside (the axon tunnel adds
    per-call latency) and a matrix big enough to be compute-bound
    (16384^2 bf16; smaller sizes are HBM-bound on v5e)."""
    n = 16384
    iters = 16
    x = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def chain(a):
        def body(_, acc):
            return jnp.matmul(acc, acc,
                              preferred_element_type=jnp.float32
                              ).astype(jnp.bfloat16)
        return jax.lax.fori_loop(0, iters, body, a)

    np.asarray(chain(x)[0, :8])  # compile + warmup
    t0 = time.perf_counter()
    out = chain(x)
    np.asarray(out[0, :8])  # host fetch drains the chain
    dt = time.perf_counter() - t0
    return iters * 2 * n ** 3 / dt


def _diff_time(run, k_small, k_large):
    """Differential step time: run(k) must execute k steps in one
    dispatch and sync.  Both k are run once to compile, once timed."""
    run(k_small)
    t0 = time.perf_counter()
    run(k_small)
    t_s = time.perf_counter() - t0
    run(k_large)
    t0 = time.perf_counter()
    run(k_large)
    t_l = time.perf_counter() - t0
    return (t_l - t_s) / (k_large - k_small)


def _run_section(name, fn, metrics_out):
    """Run one bench section with an observability-registry snapshot
    taken around it; the per-section delta (compile counts, Pallas
    route/fallback decisions, serving scheduler counters, latency
    quantiles) lands in the JSON's ``metrics`` sub-object so the BENCH
    trajectory records fallback rates and compile counts alongside
    throughput."""
    from paddle_tpu.observability import metrics as obs_metrics

    reg = obs_metrics.get_registry()
    before = reg.snapshot()
    try:
        return fn()
    finally:
        delta = obs_metrics.diff_snapshots(before, reg.snapshot())
        if delta:
            metrics_out[name] = delta


def main():
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")
    peak_flops = 197e12 if on_tpu else 1e11  # v5e nominal bf16

    metrics = {}
    result = _run_section(
        "llama_pretrain", lambda: _bench_llama(on_tpu, peak_flops), metrics)
    gc.collect()
    secondary = {}
    sections = [
        ("resnet50_train", lambda: _bench_resnet(on_tpu, peak_flops)),
        ("ocr_rec_infer", lambda: _bench_ocr(on_tpu, peak_flops)),
        ("llm_decode", lambda: _bench_decode(on_tpu)),
        ("moe_block", lambda: _bench_moe(on_tpu)),
        ("llm_serving", lambda: _bench_serving(on_tpu)),
    ]
    for name, fn in sections:
        try:
            secondary[name] = _run_section(name, fn, metrics)
        except Exception as e:
            secondary[name] = {"error": str(e)[:300]}
        gc.collect()
    result["secondary"] = secondary
    result["metrics"] = metrics
    print(json.dumps(result))


def _bench_llama(on_tpu, peak_flops):
    from paddle_tpu.models import LlamaConfig

    if on_tpu:
        dtype = "bfloat16"
        ks = (3, 10)
        # largest-fits ladder: ~1.1B params (h2048/L16/i8192); 16G HBM must
        # hold bf16 params + bf16 m/v + remat activations.  The first rung
        # trades one third of the MLP remat saves (stride 3, ~+12 ms of
        # recompute) for ~1.1 GB of HBM that lets the Pallas fused AdamW
        # kernel fit (~-38 ms of update sweep; BASELINE.md round 5) —
        # net -25 ms/step measured.  The second rung is the round-4
        # configuration (stride 2, XLA sweep) as the OOM fallback.
        ladder = [
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=16, num_attention_heads=32,
                 num_key_value_heads=8, batch=8, seq=2048,
                 stride=3, fused_adamw=True),
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=16, num_attention_heads=32,
                 num_key_value_heads=8, batch=8, seq=2048),
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=16, num_attention_heads=32,
                 num_key_value_heads=8, batch=4, seq=2048),
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=12, num_attention_heads=32,
                 num_key_value_heads=8, batch=4, seq=2048),
            dict(hidden_size=2048, intermediate_size=5632,
                 num_hidden_layers=8, num_attention_heads=16,
                 num_key_value_heads=8, batch=8, seq=1024),
        ]
    else:
        dtype = "float32"
        ks = (2, 4)
        ladder = [dict(hidden_size=256, intermediate_size=704,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, batch=2, seq=128,
                       vocab_size=1024)]

    last_err = None
    ladder_fallbacks = []
    for rung, lad in enumerate(ladder):
        batch, seq = lad.pop("batch"), lad.pop("seq")
        stride = lad.pop("stride", 2)
        fused_adamw = lad.pop("fused_adamw", False)
        cfg = LlamaConfig(vocab_size=lad.pop("vocab_size", 32000),
                          max_position_embeddings=seq,
                          recompute=on_tpu,
                          # remat dial (BASELINE.md round-4 ladder):
                          # every layer saves flash O+LSE (backward
                          # stops rematting at the q/k/v projections);
                          # every SECOND layer additionally saves the
                          # MLP gate/up outputs (skips the two big
                          # matmul recomputes) — affordable because
                          # bf16 moments (reference-default
                          # multi_precision=False, stochastic-rounding
                          # stores) free ~4.4 GB of optimizer state.
                          # The chunked fused lm_head+CE pays ~17 ms of
                          # logits-recompute but frees the ~2 GB fp32
                          # logits buffer (HBM is the binding
                          # constraint throughout)
                          recompute_policy=("save_attn_mlp" if on_tpu
                                            else None),
                          recompute_policy_alt=("save_attn" if on_tpu
                                                else None),
                          recompute_policy_stride=stride if on_tpu else 1,
                          fused_linear_loss=on_tpu,
                          **lad)
        try:
            result = _run_llama(cfg, batch, seq, ks, dtype, peak_flops,
                                on_tpu, fused_adamw=fused_adamw)
            # which rungs fell through, and WHY: a non-OOM failure of
            # the headline rung (e.g. a Mosaic lowering error) must be
            # distinguishable from an expected OOM fallback
            result["ladder_fallbacks"] = ladder_fallbacks
            return result
        except Exception as e:
            # OOM (or any rung-specific failure, e.g. a Mosaic lowering
            # error on the fused-kernel rung) -> walk down the ladder;
            # keep only the message: a traceback frame would pin the
            # failed config's params/opt state in HBM
            last_err = str(e)[:500]
            msg = str(e)
            ladder_fallbacks.append({
                "rung": rung,
                "error_class": type(e).__name__,
                "error": (msg.splitlines()[0][:200] if msg else ""),
            })
            continue
    raise RuntimeError(f"no bench llama config succeeded: {last_err}")


def _run_llama(cfg, batch, seq, ks, dtype, peak_flops, on_tpu,
               fused_adamw=False):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaForCausalLM, LlamaPretrainingCriterion

    paddle.set_flags({"FLAGS_use_fused_adamw_kernel": bool(fused_adamw)})
    try:
        return _run_llama_impl(cfg, batch, seq, ks, dtype, peak_flops,
                               on_tpu, fused_adamw)
    finally:
        paddle.set_flags({"FLAGS_use_fused_adamw_kernel": False})


def _run_llama_impl(cfg, batch, seq, ks, dtype, peak_flops, on_tpu,
                    fused_adamw):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaForCausalLM, LlamaPretrainingCriterion

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.train()
    if dtype == "bfloat16":
        model.to(dtype="bfloat16")
    criterion = LlamaPretrainingCriterion(cfg)
    # multi_precision=False is the reference AdamW DEFAULT: moments in
    # the param dtype.  Our bf16-moment stores add stochastic rounding
    # (unbiased, unlike plain RNE) — halves the optimizer state and
    # funds the save_attn_mlp remat saves above
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=False)

    if cfg.fused_linear_loss:
        def loss_fn(net, tokens, labels):
            return net(tokens, labels=labels)[0]  # logits are None (fused)
    else:
        def loss_fn(net, tokens, labels):
            logits = net(tokens)
            return criterion(logits, labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    tokens = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    def run(k):
        float(step.run_steps(tokens, labels, steps=k))

    sec_per_step = _diff_time(run, *ks)
    tokens_per_s = batch * seq / sec_per_step

    # Pallas-kernel presence check: the lowered program must contain
    # tpu_custom_call (flash attention / rms norm / rope kernels)
    pallas_in_hlo = False
    try:
        lowered = step._compiled.lower(
            [p._value for p in step._params], step._state, step._gm_state,
            jax.random.PRNGKey(0), jnp.float32(1e-4),
            [b._value for b in step._buffers],
            tokens._value, labels._value)
        pallas_in_hlo = "tpu_custom_call" in lowered.as_text()
    except Exception:
        pass

    n_params = sum(p.size for p in model.parameters())
    n_embed = model.llama.embed_tokens.weight.size
    n_matmul = n_params - n_embed  # lm_head stays (it is a matmul)
    flops_per_token = (6.0 * n_matmul +
                       6.0 * cfg.num_hidden_layers * seq * cfg.hidden_size)
    mfu = flops_per_token * tokens_per_s / peak_flops

    measured_peak = None
    if on_tpu:
        try:
            measured_peak = _measure_matmul_peak(jnp, jax)
        except Exception:
            pass

    return {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 3),
        "mfu": round(mfu, 4),
        "model_params": int(n_params),
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
                   "intermediate": cfg.intermediate_size, "batch": batch,
                   "seq": seq, "dtype": dtype,
                   "remat_stride": cfg.recompute_policy_stride,
                   "fused_adamw_kernel": bool(fused_adamw)},
        "flops_per_token": round(flops_per_token / 1e9, 3),
        "peak_flops_nominal": peak_flops,
        "measured_matmul_flops": (round(measured_peak / 1e12, 1) * 1e12
                                  if measured_peak else None),
        "pallas_in_hlo": pallas_in_hlo,
    }


def _bench_resnet(on_tpu, peak_flops):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.utils.flops import count_matmul_flops
    from paddle_tpu.vision.models import resnet50

    if on_tpu:
        batch, size, ks, dtype = 128, 224, (5, 25), "bfloat16"
    else:
        batch, size, ks, dtype = 4, 64, (2, 4), "float32"

    paddle.seed(0)
    net = resnet50(num_classes=1000)
    net.train()
    if dtype == "bfloat16":
        net.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())

    def loss_fn(net, x, y):
        return F.cross_entropy(net(x), y).mean()

    step = TrainStep(net, loss_fn, opt)
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        rng.standard_normal((batch, 3, size, size)).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")
    y = paddle.to_tensor(rng.integers(0, 1000, (batch,)).astype(np.int64))

    def run(k):
        float(step.run_steps(x, y, steps=k))

    sec_per_step = _diff_time(run, *ks)
    images_per_s = batch / sec_per_step

    net.eval()
    fwd_flops = count_matmul_flops(
        lambda xa: net(paddle.Tensor(xa))._value, x)
    net.train()
    train_flops = 3 * fwd_flops  # fwd + dgrad + wgrad convention
    conv_mfu = train_flops / batch * images_per_s / peak_flops
    return {
        "images_per_s": round(images_per_s, 1),
        "step_ms": round(sec_per_step * 1e3, 2),
        "conv_mfu": round(conv_mfu, 4),
        "mfu_bar": 0.30,  # measured roofline: BN/elementwise HBM-bound
        "batch": batch, "image": size, "dtype": dtype,
        "fwd_gflops_per_image": round(fwd_flops / batch / 1e9, 3),
    }


def _bench_ocr(on_tpu, peak_flops):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.ocr import CRNN, CRNNConfig
    from paddle_tpu.utils.flops import count_matmul_flops

    if on_tpu:
        # wide differential interval: at ~7 ms/fwd a (4,16) spread is an
        # ~84 ms delta, inside the tunnel's tens-of-ms jitter — measured
        # 51k..83k img/s swings across runs (BASELINE.md reconciliation);
        # (8,72) puts the delta at ~450 ms
        batch, width, dtype, ks = 512, 320, "bfloat16", (8, 72)
    else:
        batch, width, dtype, ks = 8, 64, "float32", (2, 4)

    paddle.seed(0)
    net = CRNN(CRNNConfig(image_height=32))
    net.eval()
    if dtype == "bfloat16":
        net.to(dtype="bfloat16")
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(
        rng.standard_normal((batch, 3, 32, width)).astype(np.float32))
    if dtype == "bfloat16":
        x = x.astype("bfloat16")

    params = [p._value for p in net.parameters()]
    buffers = [b._value for b in net.buffers()]

    import jax.numpy as jnp

    def fwd(pv, bv, xa, n):
        # chain n forwards in-graph so dispatch latency amortizes
        saved = [p._value for p in net.parameters()]
        saved_b = [b._value for b in net.buffers()]
        try:
            for p, a in zip(net.parameters(), pv):
                p._value = a
            for b, a in zip(net.buffers(), bv):
                b._value = a

            def body(carry, _):
                # carry feeds the next input so iterations form a true
                # serial chain (a loop-invariant body would let XLA hoist
                # the model out of the scan and run it once)
                out = net(paddle.Tensor(xa + carry))._value
                m = out.mean().astype(xa.dtype)
                return m * jnp.asarray(1e-3, xa.dtype), m

            _, outs = jax.lax.scan(body, jnp.zeros((), xa.dtype), None,
                                   length=n)
            return outs.sum()
        finally:
            for p, s in zip(net.parameters(), saved):
                p._value = s
            for b, s in zip(net.buffers(), saved_b):
                b._value = s

    jfwd = jax.jit(fwd, static_argnums=3)

    def run(k):
        float(jfwd(params, buffers, x._value, k))

    sec_per_fwd = _diff_time(run, *ks)
    images_per_s = batch / sec_per_fwd
    fwd_flops = count_matmul_flops(
        lambda xa: net(paddle.Tensor(xa))._value, x)
    mfu = fwd_flops / batch * images_per_s / peak_flops
    return {
        "images_per_s": round(images_per_s, 1),
        "fwd_ms": round(sec_per_fwd * 1e3, 2),
        "mfu": round(mfu, 4),
        "batch": batch, "image": [32, width], "dtype": dtype,
        "fwd_gflops_per_image": round(fwd_flops / batch / 1e9, 3),
    }


def _bench_moe(on_tpu):
    """MoE block forward (VERDICT r3 item 4): scatter vs dense dispatch
    at Llama-block scale; tools/bench_moe.py has the full E/capacity
    sweep (BASELINE.md table).  MFU counts EXPERT matmul FLOPs only —
    the dense path's [T,E,C] dispatch einsums are overhead (they cost
    2*T^2*k*cf*D FLOPs, independent of E, quadratic in tokens)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "bench_moe", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tools", "bench_moe.py"))
    bm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bm)
    if on_tpu:
        kw = {}
        peak = 197e12
    else:
        kw = dict(T=256, D=64, F=128, steps=(1, 3))
        peak = 1e11
    s_ms, C, flops = bm.bench_case(8, 1.25, "scatter", **kw)
    d_ms, _, _ = bm.bench_case(8, 1.25, "dense", **kw)
    return {
        "experts": 8, "top_k": 2, "capacity_factor": 1.25, "capacity": C,
        "scatter_fwd_ms": round(s_ms, 2), "dense_fwd_ms": round(d_ms, 2),
        "expert_gflops": round(flops / 1e9, 1),
        "scatter_mfu": round(flops / (s_ms / 1e3) / peak, 4),
    }


def _bench_decode(on_tpu):
    """Cached-KV autoregressive serving (the fused_multi_transformer
    role): decode tokens/s at b1 and b32, prefill tokens/s, bf16 and
    weight-only int8.  Decode is weight-streaming bound — the roofline
    is tokens/s ~= B * HBM_BW / (weight_bytes + B*kv_sweep_bytes) — so
    achieved GB/s is reported alongside.

    Timing: one generate() call is ONE dispatch (prefill + lax.scan);
    decode sec/token comes from the differential between two
    max_new_tokens settings at the SAME max_cache_len (identical
    per-step cost), so tunnel dispatch/fetch constants cancel.  Prefill
    is timed by a chained scan of the serving prefill program.
    """
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.models.generation import GenerationConfig, model_arrays
    from paddle_tpu.inference.llm import _build_serving_fns

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=8192, num_hidden_layers=16,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096)
        # wide differentials: at ~2-3 ms/step the delta must dwarf the
        # tunnel's tens-of-ms jitter (same lesson as the OCR interval)
        prompt, n_small, n_large = 128, 32, 288
        cache_ladder = [2048, 1024, 512]
        batches = (1, 32)
        compute_dtype = "bfloat16"
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          intermediate_size=704, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=512)
        prompt, n_small, n_large = 16, 4, 12
        cache_ladder = [64]
        batches = (1, 4)
        compute_dtype = "float32"

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)   # f32-stored; cast hoisted per call
    model.eval()
    rng = np.random.default_rng(0)

    n_params = sum(p.size for p in model.parameters())
    n_embed = model.llama.embed_tokens.weight.size
    n_head_w = model.lm_head.weight.size
    kv_slot_bytes = (cfg.num_hidden_layers * 2 * cfg.num_key_value_heads *
                     cfg.head_dim * 2)          # bf16 cache, k+v

    def measure(tag, weight_bytes):
        per_b = {}
        for b in batches:
            ids = paddle.to_tensor(
                rng.integers(0, cfg.vocab_size, (b, prompt))
                .astype(np.int32))
            last = None
            for cache_len in cache_ladder:
                try:
                    def run(n):
                        toks = model.generate(
                            ids, max_new_tokens=n,
                            max_cache_len=cache_len,
                            compute_dtype=compute_dtype)
                        np.asarray(toks._value)   # true sync on axon
                    run(n_small)
                    t0 = time.perf_counter()
                    run(n_small)
                    t_s = time.perf_counter() - t0
                    run(n_large)
                    t0 = time.perf_counter()
                    run(n_large)
                    t_l = time.perf_counter() - t0
                    step_s = (t_l - t_s) / (n_large - n_small)
                    # the flash-decode kernel streams ONLY the valid
                    # prefix (round 5) — when it routes, the per-step
                    # KV sweep is the average valid length over the
                    # differential window; the XLA fallback still
                    # sweeps the full static cache
                    # ask the kernel's OWN routing gate (flag + Mosaic
                    # probe + geometry/VMEM checks) with the real
                    # shapes, so the sweep basis matches the code path
                    # that actually ran
                    from paddle_tpu.ops.pallas.decode_attention import (
                        DEFAULT_CHUNK, cache_shape, decode_attn_sig,
                        should_use_pallas)
                    hkv_ = cfg.num_key_value_heads
                    d_ = cfg.head_dim
                    g_ = cfg.num_attention_heads // hkv_
                    cdt = jnp.dtype(compute_dtype)
                    prefix_aware = should_use_pallas(
                        jax.ShapeDtypeStruct((b, hkv_, g_, d_), cdt),
                        jax.ShapeDtypeStruct(
                            cache_shape(b, hkv_, cache_len, d_), cdt))
                    avg_valid = prompt + (n_small + n_large) // 2
                    kchunk = None
                    if prefix_aware:
                        # the kernel streams whole chunk-granular DMAs
                        # (n_chunks = lens // chunk + 1): round the
                        # swept length UP to the tuned chunk, mirroring
                        # the kernel's own n_chunks computation, so
                        # achieved_GBps stays comparable across chunk
                        # tunings
                        from paddle_tpu.ops.pallas.schedule_search \
                            import get_schedule
                        hit = get_schedule(
                            "decode_attention",
                            decode_attn_sig(b, hkv_, g_, cache_len, d_,
                                            cdt))
                        kchunk = int(hit) if hit else DEFAULT_CHUNK
                        while cache_len % kchunk:
                            kchunk //= 2
                        # EXACTLY the kernel's DMA count: it issues
                        # lens // chunk + 1 chunks for last-valid-index
                        # lens = avg_valid - 1, i.e. ceil(avg_valid /
                        # chunk) whole chunks — the old "// + 1" form
                        # overshot by one full chunk whenever avg_valid
                        # landed on a chunk boundary, skewing
                        # achieved_GBps across chunk tunings
                        swept_len = min(
                            cache_len,
                            ((avg_valid - 1) // kchunk + 1) * kchunk)
                    else:
                        swept_len = cache_len
                    swept = weight_bytes + b * swept_len * kv_slot_bytes
                    last = {
                        "decode_tokens_per_s": round(b / step_s, 1),
                        "step_ms": round(step_s * 1e3, 3),
                        "cache_len": cache_len,
                        "kv_swept_len": swept_len,
                        "kv_chunk": kchunk,
                        "achieved_GBps": round(swept / step_s / 1e9, 1),
                    }
                    break
                except Exception as e:
                    if "RESOURCE_EXHAUSTED" in str(e) or \
                            "Out of memory" in str(e):
                        continue
                    raise
            if last is None:
                raise RuntimeError("no decode config fit in memory")
            # prefill: chained scan of the serving prefill program; the
            # carry mixes in the emitted token AND a cache slice so
            # neither the forward nor the cache writes can be DCE'd
            gcfg = GenerationConfig(compute_dtype=compute_dtype)
            prefill, _ = _build_serving_fns(model, b, last["cache_len"],
                                            gcfg, 1)
            params, buffers = model_arrays(model)
            pb = [p._value for p in params] + [bf._value for bf in buffers]
            lens0 = jnp.full((b,), prompt, jnp.int32)
            key0 = jax.random.PRNGKey(0)

            def chained(pbv, ids_a, k):
                def body(carry, _):
                    # prefill returns (tok0, lens, done, key, *kv planes)
                    out = prefill(pbv, carry, lens0, key0)
                    tok0, kc0 = out[0], out[4]
                    feed = (tok0[:, None] +
                            kc0.reshape(b, -1)[:, :1].astype(jnp.int32))
                    return (carry + feed) % cfg.vocab_size, tok0[0]
                _, toks = jax.lax.scan(body, ids_a, None, length=k)
                return toks.sum()

            jc = jax.jit(chained, static_argnums=2)

            def prun(k):
                np.asarray(jc(pb, ids._value, k))

            # per-prefill ms scales with b: short prefills need long
            # chains for the delta to clear jitter
            kp = ((8, 56) if b <= 4 else (4, 12)) if on_tpu else (1, 3)
            prun(kp[0])
            t0 = time.perf_counter()
            prun(kp[0])
            tp_s = time.perf_counter() - t0
            prun(kp[1])
            t0 = time.perf_counter()
            prun(kp[1])
            tp_l = time.perf_counter() - t0
            pre_s = (tp_l - tp_s) / (kp[1] - kp[0])
            last["prefill_ms"] = round(pre_s * 1e3, 2)
            last["prefill_tokens_per_s"] = round(b * prompt / pre_s, 1)
            per_b[f"b{b}"] = last
        return per_b

    out = {"config": {"params": int(n_params), "prompt": prompt,
                      "dtype": compute_dtype,
                      "n_small": n_small, "n_large": n_large}}
    # bf16: weights stream as the hoisted bf16 copy (2 B/param, embedding
    # excluded: decode gathers one row)
    out["bf16"] = measure("bf16", (n_params - n_embed) * 2)
    # int8 quality gate (VERDICT r4 weak #6): teacher-forced NLL on a
    # held-out stream + greedy token agreement, bf16 vs int8 on THIS
    # model (tools/bench_int8_quality.py has the full-size version).
    # Random weights make absolute PPL meaningless but the bf16-int8
    # DELTA is a faithful quantization-error measure; greedy agreement
    # decays after the first near-tie divergence, so the first
    # divergence step is reported alongside.
    def _nll(ids_np):
        from paddle_tpu.models.generation import model_arrays, swap_call
        params, buffers = model_arrays(model)

        def pure(p_values, b_values, ids):
            def run():
                logits = model(paddle.Tensor(ids))._value
                lp = jax.nn.log_softmax(
                    logits[:, :-1].astype(jnp.float32), -1)
                nll = -jnp.take_along_axis(
                    lp, ids[:, 1:][..., None].astype(jnp.int32), -1)
                return nll.mean()
            return swap_call(params, buffers, p_values, b_values,
                             compute_dtype, run)
        return float(jax.jit(pure)(
            [p._value for p in params], [bf._value for bf in buffers],
            jnp.asarray(ids_np)))

    q_stream = rng.integers(0, cfg.vocab_size,
                            (2, 1024 if on_tpu else 128)).astype(np.int32)
    q_prompts = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32))
    q_new = 128 if on_tpu else 8

    def _greedy():
        return np.asarray(model.generate(
            q_prompts, max_new_tokens=q_new, max_cache_len=32 + q_new,
            compute_dtype=compute_dtype)._value)

    nll_bf16 = _nll(q_stream)
    toks_bf16 = _greedy()

    # weight-only int8: Linears stream 1 B/param; lm_head kept float
    from paddle_tpu.quantization import weight_only_quantize
    weight_only_quantize(model, skip=lambda name, l: name == "lm_head")
    model._generate_exe_cache = {}
    paddle.set_flags({"FLAGS_use_int8_matmul_kernel": True})
    try:
        out["int8"] = measure(
            "int8", (n_params - n_embed - n_head_w) * 1 + n_head_w * 2)
        nll_int8 = _nll(q_stream)
        toks_int8 = _greedy()
    finally:
        paddle.set_flags({"FLAGS_use_int8_matmul_kernel": False})
    agree = toks_bf16 == toks_int8
    out["int8_quality"] = {
        "delta_ppl_pct": round(
            100 * (float(np.exp(nll_int8)) / float(np.exp(nll_bf16))
                   - 1), 3),
        "token_agreement_pct": round(100 * float(agree.mean()), 2),
        "first_divergence_step": [
            int(np.argmin(row)) if not row.all() else int(row.size)
            for row in agree],
        "greedy_steps": int(agree.size),
        "eval_tokens": int(q_stream.size),
    }
    return out


def _bench_serving(on_tpu):
    """Continuous batching vs static batching on the SAME mixed-length
    Poisson-ish arrival trace (the llm_serving metric).

    Both arms run the IDENTICAL compiled programs — the slot-granular
    prefill and the shared decode block of
    ``paddle_tpu/inference/serving.py`` — the static arm merely gang-
    schedules (admit only into an empty pool, the LLMPredictor
    admission discipline), so the tokens/s delta isolates the
    scheduler: with mixed request lengths, static batching wastes
    (max_len - mean_len)/max_len of its decode steps on finished slots
    while continuous batching refills them.  Reported per arm:
    useful tokens/s, p50/p99 per-request latency (arrival -> last
    token), and mean slot occupancy over decode steps.

    A third A/B isolates the PAGED prefix cache: the same trace where
    70% of requests share a system prompt runs with
    ``enable_prefix_cache`` on and off — matched blocks skip whole
    prefill chunks, so the deltas are tokens/s, p50 TTFT and prefill-
    chunk count, alongside the block-granular hit rate and the pool's
    blocks-in-use high-water mark (the capacity paging frees).

    A ``prefix_tiered`` sub-object isolates the TIERED RADIX prefix
    cache: a multi-turn conversation trace (deep shared system prompt,
    growing per-conversation histories) over a deliberately small HBM
    pool runs in three modes — tiered radix (demote-to-host-RAM +
    exact-bytes swap-in), the PR-3 digest cache (reclaim forgets) and
    no cache — with identical token traces (outputs are engine-exact),
    so the deltas are pure cache effectiveness: token-granular hit
    volume, mean TTFT, host swap-in traffic and prefill-chunk count.

    A fourth A/B isolates SPECULATIVE DECODING: a repetitive/structured
    trace (tiled token patterns) runs with ``spec_decode=K`` (n-gram
    self-drafting + the K+1-position paged verify forward) and without
    — the deltas are tokens/s plus the acceptance economics
    (accepted-length distribution, acceptance rate, drafts-per-token),
    which also land in the run's ``metrics`` sub-object through the
    ``serving.spec.*`` instruments.

    A ``sampling`` sub-object reruns the spec arm's trace greedy vs
    stochastically sampled (per-request temperature/top-k + seeds) vs
    spec + sampled — pricing the sampling chain on the decode path and
    reporting what temperature does to speculative acceptance
    (accepted-length delta vs greedy spec, residual-resample count).

    A fifth A/B isolates the INT8 KV CACHE (``kv_int8`` sub-object):
    the mixed trace replayed through ``kv_cache_dtype="int8"`` vs the
    full-precision engine — tokens/s ratio, modeled achieved_GBps per
    arm (``serving.kv.bytes_swept`` / wall), and the quality gate
    (teacher-forced greedy token agreement >= 0.98 and |dNLL| <= 1%
    through the paged cache path, mirroring the weight-int8 gate of
    ``_bench_decode``).

    A ``weight_quant`` sub-object replays the same trace through
    ``weight_dtype="int8"`` and ``"int4"`` engines vs the
    full-precision baseline — tokens/s report-only (on CPU the XLA
    dequant fallback serves the quantized arms), gated on
    deterministic counters: the kv_int8-style teacher-forced quality
    gate (int8 gates on token agreement >= 0.98 over DECISIVE
    positions — baseline top-2 logit margin > 0.01 — AND |dNLL| <=
    1%; int4 gates on dNLL only, agreement report-only — 4-bit
    weight noise flips genuinely-decided argmaxes on a random-init
    model), the
    modeled weight sweep strictly decreasing baseline > int8 > int4,
    dispatch-count parity across arms (scheduling identity), and the
    route-counter proof that 128-aligned shapes dispatch the Pallas
    dequant-matmul kernel for both bit widths.

    A sixth A/B isolates OVERLOAD RESILIENCE (``overload``
    sub-object): a bursty trace whose long low-priority requests pin
    the block pool against a burst of short high-priority ones, run
    with KV preemption + host-RAM swap ON vs OFF — the deltas are the
    interactive class's p99 TTFT and, under a queue-delay SLO,
    the completion rate (the no-preempt arm sheds-by-timeout what it
    cannot serve in time), plus a bounded-queue shed demo.

    The spec and overload arms each carry a ``goodput`` sub-object
    (PR 9's ledger): useful vs wasted dispatched token-positions with
    per-reason waste, gated ONLY on deterministic token counts — the
    conservation gate is exact integer equality (useful + wasted ==
    dispatched).  Wall-shaped companions (``mean_tpot_ms``, SLO
    attainment, the ``serving.step.{host,dispatch}_seconds`` split in
    the run's ``metrics`` sub-object) are reported ungated.

    An ``async`` sub-object isolates the DISPATCH-AHEAD step pipeline
    (PR 10): the mixed drain trace through ``async_dispatch=True`` vs
    the lockstep kill-switch on private registries, gated only on
    deterministic counters (byte-identical outputs, equal dispatch/
    token counts, harvests > 0 with forced syncs confined to the
    documented reasons); the host/dispatch/overlap second sums and
    tokens/s ride along ungated.

    A ``lora`` sub-object isolates MULTI-TENANT BATCHED LoRA SERVING
    (PR 11): tokens/s at K = 1/4/8 adapters round-robined over a
    fixed batch (paged AdapterStore + gathered-A/B decode), gated on
    deterministic counters only — K=1 batched output token-exact vs
    merged-weights ``generate()``, gather count == dispatch count —
    plus the two-tenant starvation trace FIFO vs fair-share
    (deficit-WRR): the steady tenant's completion count at a fixed
    step budget must strictly improve and the reorder counter must
    fire; steady-tenant p99 TTFT rides along report-only.

    A ``router`` sub-object isolates the FRONT-DOOR ROUTER (PR 12):
    the multi-turn + per-conversation-adapter trace through a
    2-replica ``Router`` with affinity routing (prefix + adapter
    residency as a strict tie-break inside an equal-load class) vs
    round-robin, on engine-identical traces over private registries.
    Gated ONLY on deterministic counters: per-request token-exact
    outputs across arms, prefix hit tokens strictly HIGHER under
    affinity, adapter swap-ins strictly LOWER; tokens/s rides along
    report-only (wall clock on this box is jitter-bound).
    """
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.inference.serving import ServingEngine

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=8192, num_hidden_layers=16,
                          num_attention_heads=32, num_key_value_heads=8,
                          max_position_embeddings=4096)
        num_slots, prompt, cache_len = 8, 128, 1024
        n_requests, steps_per_call = 32, 8
        new_lo, new_hi = 16, 256
        mean_gap = 0.02
        compute_dtype = "bfloat16"
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          intermediate_size=704, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=512)
        num_slots, prompt, cache_len = 4, 16, 128
        n_requests, steps_per_call = 16, 4
        new_lo, new_hi = 4, 48
        mean_gap = 0.002
        compute_dtype = "float32"

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size,
                           (n_requests, prompt)).astype(np.int32)
    plens = rng.integers(max(1, prompt // 2), prompt + 1,
                         n_requests).astype(np.int32)
    news = rng.integers(new_lo, new_hi + 1, n_requests).astype(np.int32)
    gaps = rng.exponential(mean_gap, n_requests)
    offsets = np.cumsum(gaps) - gaps[0]        # first arrives at t0

    def run_arm(static):
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=steps_per_call,
            compute_dtype=compute_dtype, static_batching=static)
        # warm the compiled programs (slot prefill + BOTH block sizes:
        # max_new = steps_per_call + 2 forces a full block then a
        # single-step tail) outside the timed window
        for _ in range(2):
            eng.submit(prompts[0][:int(plens[0])],
                       max_new_tokens=steps_per_call + 2)
        eng.run()
        warm = eng.stats()       # snapshot: exclude warm-up from occ
        t0 = time.perf_counter()
        for i in range(n_requests):
            eng.submit(prompts[i][:int(plens[i])],
                       max_new_tokens=int(news[i]),
                       arrival_time=t0 + float(offsets[i]))
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        lat = np.asarray(sorted(r.latency for r in done))
        final = eng.stats()
        dsteps = final["decode_steps"] - warm["decode_steps"]
        busy = final["busy_slot_steps"] - warm["busy_slot_steps"]
        occ = busy / (dsteps * num_slots) if dsteps else 0.0
        return {
            "tokens_per_s": round(float(news.sum()) / wall, 1),
            "p50_latency_ms": round(
                float(np.percentile(lat, 50)) * 1e3, 1),
            "p99_latency_ms": round(
                float(np.percentile(lat, 99)) * 1e3, 1),
            "mean_slot_occupancy": round(float(occ), 4),
            "wall_s": round(wall, 3),
        }

    cont = run_arm(static=False)
    stat = run_arm(static=True)

    # -- shared-prefix arm: 70% of requests share a system prompt; the
    # SAME trace runs with and without prefix caching, so the delta
    # isolates block reuse (matched blocks skip prefill chunks) --
    if on_tpu:
        pf_prompt, pf_block, pf_chunk, pf_shared = 128, 16, 32, 64
        pf_cache = 1024
    else:
        # shared prefix = 3 full blocks: a hit skips 3 of the ~4
        # chunks, so the win survives this box's wall-clock noise
        pf_prompt, pf_block, pf_chunk, pf_shared = 32, 8, 8, 24
        pf_cache = 128
    shared_ids = rng.integers(0, cfg.vocab_size,
                              pf_shared).astype(np.int32)
    # short fixed decode budget: the arm isolates PREFILL economics —
    # with decode work dominating the wall clock, the chunk savings
    # would drown in this box's scheduling noise
    pf_new = steps_per_call + 2
    pf_specs = []
    for i in range(2 * n_requests):    # longer trace: noise averages out
        n = int(rng.integers(pf_shared + 4, pf_prompt + 1))
        ids = rng.integers(0, cfg.vocab_size, n).astype(np.int32)
        if rng.random() < 0.7:
            ids[:pf_shared] = shared_ids
        pf_specs.append((ids, pf_new))

    def _one_prefix_trace(prefix_cache):
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=pf_prompt,
            max_cache_len=pf_cache, steps_per_call=steps_per_call,
            block_len=pf_block, chunk_len=pf_chunk,
            enable_prefix_cache=prefix_cache,
            compute_dtype=compute_dtype)
        for _ in range(2):     # warm chunk program + both block sizes
            eng.submit(prompts[0][:int(plens[0])],
                       max_new_tokens=steps_per_call + 2)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        # all requests arrive at t0 (drain benchmark): scheduling is
        # deterministic, so the A/B delta is block reuse, not arrival
        # jitter on a loaded box — TTFT here includes queue wait, which
        # is exactly where skipped chunks pay off
        for ids, mn in pf_specs:
            eng.submit(ids, max_new_tokens=mn, arrival_time=t0)
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        # MEAN ttft, not p50: with drain scheduling the cache's queue-
        # wait savings accrue to late-wave requests; the median sits on
        # an early-wave request and under-reports the effect
        ttft = float(np.mean([r.ttft for r in done]))
        final = eng.stats()
        # hit rate over the TIMED trace only: the second (identical)
        # warm-up request scores hits of its own, so counters are
        # warm-diffed like prefill_chunks
        hits = final["prefix_hits"] - warm["prefix_hits"]
        misses = final["prefix_misses"] - warm["prefix_misses"]
        return wall, ttft, {
            "prefix_hit_rate": round(
                hits / (hits + misses) if hits + misses else 0.0, 4),
            "prefill_chunks": final["prefill_chunks"]
            - warm["prefill_chunks"],
            # lifetime pool high-water mark; the warm-up's footprint
            # (2 small requests) is far below the trace's peak
            "peak_blocks_in_use": final["peak_blocks_in_use"],
        }

    def run_prefix_arm(prefix_cache):
        # the trace is deterministic per arm (drain scheduling, fixed
        # seeds) but this box's wall clock is not: take best-of-2 so
        # the A/B reflects the work difference, not scheduler jitter
        runs = [_one_prefix_trace(prefix_cache) for _ in range(2)]
        wall = min(r[0] for r in runs)
        ttft = min(r[1] for r in runs)
        out = dict(runs[0][2])
        out["tokens_per_s"] = round(
            float(pf_new * len(pf_specs)) / wall, 1)
        out["mean_ttft_ms"] = round(ttft * 1e3, 1)
        return out

    pfx_on = run_prefix_arm(prefix_cache=True)
    pfx_off = run_prefix_arm(prefix_cache=False)

    # -- tiered radix prefix-cache arm: multi-turn conversations with
    # a deep shared system prompt over a DELIBERATELY small HBM pool,
    # so every turn's blocks are reclaimed while the other
    # conversations run.  Three modes on the SAME trace (greedy
    # outputs are engine-exact, so the histories — and therefore the
    # traces — are identical across arms): the tiered radix cache
    # demotes reclaimed spans to host RAM and swaps the exact bytes
    # back on hit, the PR-3 digest cache forgets them, no-cache
    # recomputes everything --
    import jax.numpy as _jnp

    from paddle_tpu.observability.metrics import MetricsRegistry

    if on_tpu:
        tr_prompt, tr_block, tr_chunk, tr_sys = 256, 16, 64, 96
        tr_blocks, tr_turns, tr_convs, tr_new, tr_user = 48, 3, 4, 8, 16
    else:
        # chunks are 32-token forwards (the work a hit SAVES) and
        # blocks are 8 tokens (few, large demote/promote parcels — the
        # swap overhead a hit PAYS is per-dispatch on this box).  The
        # pool holds 12 blocks = 96 tokens against ~36 blocks of
        # final-turn conversation state: demotion pressure starts in
        # turn 1, so turns 2-4 really serve from the host tier.
        tr_prompt, tr_block, tr_chunk, tr_sys = 64, 8, 32, 24
        tr_blocks, tr_turns, tr_convs, tr_new, tr_user = 12, 4, 4, 4, 6
    tr_cache = tr_prompt + tr_new + tr_block
    tr_sys_ids = rng.integers(0, cfg.vocab_size,
                              tr_sys).astype(np.int32)

    def _one_tiered_trace(mode):
        # private registry: the three arms are COMPARED, and stats()
        # deltas on the shared registry would absorb each other
        eng = ServingEngine(
            model, num_slots=1 if not on_tpu else 2,
            prompt_len=tr_prompt,
            max_cache_len=tr_cache, steps_per_call=steps_per_call,
            block_len=tr_block, chunk_len=tr_chunk,
            num_blocks=tr_blocks, prefix_cache_mode=mode,
            host_cache_blocks=8 * tr_blocks,
            compute_dtype=compute_dtype, registry=MetricsRegistry())
        eng.submit(tr_sys_ids, max_new_tokens=steps_per_call + 2)
        eng.run()                           # warm chunk+block programs
        if mode == "radix":
            # warm the demote/preempt gather and the promote scatter
            # (both table-width) against the trash row, outside the
            # timed window — first-use compiles would otherwise land
            # inside the first turn's TTFT (this engine is fresh; jit
            # caches are per-closure)
            row = np.full((eng.max_blocks,), eng._pool.trash, np.int32)
            g = eng._swap_out()(_jnp.asarray(row), *eng._arenas)
            padded = [
                _jnp.asarray(np.zeros_like(np.asarray(r))) for r in g]
            outp = eng._swap_in()(
                _jnp.asarray(row), *padded, *eng._arenas)
            eng._arenas = list(outp)
        warm = eng.stats()
        arng = np.random.default_rng(7)     # identical trace per arm
        hist = [list(tr_sys_ids) for _ in range(tr_convs)]
        ttfts, toks = [], 0
        t0 = time.perf_counter()
        for _turn in range(tr_turns):
            reqs = []
            for ci in range(tr_convs):
                user = arng.integers(0, cfg.vocab_size,
                                     tr_user).astype(np.int32)
                hist[ci].extend(int(x) for x in user)
                ids = np.asarray(hist[ci], np.int32)
                # arrival = submit time (NOT t0): a turn only exists
                # after the previous one answered, so anchoring ttft
                # at trace start would charge turn N all prior turns'
                # wall time instead of its own queue-wait + prefill
                reqs.append((ci, eng.submit(ids,
                                            max_new_tokens=tr_new)))
            done = {r.request_id: r for r in eng.run()}
            for ci, r in reqs:
                out = done[r.request_id].output
                hist[ci].extend(int(x) for x in out)
                ttfts.append(r.ttft)
                toks += out.size
        wall = time.perf_counter() - t0
        s = eng.stats()
        return {
            "tokens_per_s": round(toks / wall, 1),
            "mean_ttft_ms": round(float(np.mean(ttfts)) * 1e3, 1),
            "hit_tokens": s["prefix_hit_tokens"]
            - warm["prefix_hit_tokens"],
            "partial_hits": s["prefix_partial_hits"],
            "host_hits": s["prefix_host_hits"],
            "host_swapin_blocks": s["host_swapin_blocks"],
            "swapin_bytes": s["swap_bytes_in"] - warm["swap_bytes_in"],
            "prefill_chunks": s["prefill_chunks"]
            - warm["prefill_chunks"],
        }

    def _tiered_arm(mode):
        # best-of-3 walls, same rationale as the prefix arm's
        # best-of-2 (counters are trace-deterministic, the wall clock
        # on this box is not) with one more rep: the arms run minutes
        # apart and the box drifts, so the min needs more support
        runs = [_one_tiered_trace(mode) for _ in range(3)]
        out = dict(runs[0])
        out["tokens_per_s"] = max(r["tokens_per_s"] for r in runs)
        out["mean_ttft_ms"] = min(r["mean_ttft_ms"] for r in runs)
        return out

    tier_r = _tiered_arm("radix")
    tier_d = _tiered_arm("digest")
    tier_n = _tiered_arm("none")

    # -- dispatch-ahead arm: the SAME mixed drain trace through two
    # engines that differ ONLY in async_dispatch (the plan/harvest
    # pipeline vs the lockstep kill-switch).  PRIVATE registries (the
    # arms are compared, and shared-registry deltas would absorb each
    # other).  Gated ONLY on deterministic counters: byte-identical
    # outputs, equal dispatch/token counts, harvests > 0 with forced
    # syncs confined to the documented reasons this trace can produce
    # (budget exhaustion + final prefill chunks — no EOS, spec, mask
    # or preemption here).  Wall-shaped numbers (tokens/s, the
    # host/dispatch/overlap second sums) are reported ungated: on the
    # 2-core CI box JAX's async dispatch overlaps little, the shape of
    # the split is what real accelerators read --
    def _one_async_trace(async_dispatch):
        reg = MetricsRegistry()
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=steps_per_call,
            block_len=pf_block, compute_dtype=compute_dtype,
            registry=reg, async_dispatch=async_dispatch)
        for _ in range(2):     # warm chunk program + both block sizes
            eng.submit(prompts[0][:int(plens[0])],
                       max_new_tokens=steps_per_call + 2)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        for i in range(n_requests):
            eng.submit(prompts[i][:int(plens[i])],
                       max_new_tokens=int(news[i]), arrival_time=t0)
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()

        def _hsum_ms(name):
            return round(reg.get(name).summary()["sum"] * 1e3, 3)

        counts = {k: final[k] - warm[k] for k in (
            "block_dispatches", "prefill_chunks", "decode_steps",
            "dispatched_tokens", "useful_tokens", "wasted_tokens",
            "async_syncs", "async_harvests")}
        counts["syncs_by_reason"] = {
            k: final["async_syncs_by_reason"][k]
            - warm["async_syncs_by_reason"][k]
            for k in final["async_syncs_by_reason"]}
        walls = {"host_ms": _hsum_ms("serving.step.host_seconds"),
                 "dispatch_ms": _hsum_ms("serving.step.dispatch_seconds"),
                 "overlap_ms": _hsum_ms("serving.step.overlap_seconds")}
        return wall, counts, walls, np.concatenate(
            [r.output for r in done])

    def run_async_arm(async_dispatch):
        # best-of-2 walls; counters/outputs are deterministic per arm
        runs = [_one_async_trace(async_dispatch) for _ in range(2)]
        wall = min(r[0] for r in runs)
        return wall, runs[0][1], runs[0][2], runs[0][3]

    as_wall, as_c, as_w, as_out = run_async_arm(True)
    sy_wall, sy_c, sy_w, sy_out = run_async_arm(False)
    as_fired = {k: v for k, v in as_c["syncs_by_reason"].items() if v}
    async_ab = {
        "tokens_per_s": round(float(news.sum()) / as_wall, 1),
        "sync_tokens_per_s": round(float(news.sum()) / sy_wall, 1),
        "vs_sync": round(sy_wall / max(as_wall, 1e-9), 3),
        "async_syncs": as_c["async_syncs"],
        "async_harvests": as_c["async_harvests"],
        "syncs_by_reason": as_fired,
        # wall-shaped step split per arm — reported, never gated
        "host_ms": as_w["host_ms"],
        "dispatch_ms": as_w["dispatch_ms"],
        "overlap_ms": as_w["overlap_ms"],
        "sync_host_ms": sy_w["host_ms"],
        "sync_dispatch_ms": sy_w["dispatch_ms"],
        "gate": {
            "token_exact": bool((as_out == sy_out).all()),
            "dispatch_counts_equal": all(
                as_c[k] == sy_c[k] for k in (
                    "block_dispatches", "prefill_chunks",
                    "decode_steps", "dispatched_tokens",
                    "useful_tokens", "wasted_tokens")),
            "pipelined": (as_c["async_harvests"] > 0
                          and as_c["async_syncs"] > 0
                          and sy_c["async_harvests"] == 0
                          and sy_c["async_syncs"] == 0),
            "sync_reasons_documented": set(as_fired) <= {
                "budget", "chunk_final"},
        },
    }

    # -- depth-S dispatch-ahead arm (in-trace finish bitmap + fused
    # multi-iteration windows): an EOS-CONFIGURED drain trace —
    # exactly the shape where the depth-1 pipeline pays its dominant
    # forced sync (reason "eos", once per iteration, because EOS
    # detection is host-semantic there) — through async_depth=1 vs
    # async_depth=S vs the lockstep kill-switch.  One request per
    # slot, all arriving at t0, so after the prefill phase the queue
    # is empty and the windows are provably eventless: depth S reads
    # EOS from the device-side finish bitmap one harvest late
    # (deterministic lag, flight-recorder-stamped) and dispatches S
    # iterations as ONE fused scan program.  PRIVATE registries and
    # recorders; gates DETERMINISTIC only: token-exact across all
    # three arms, admission order identical, per-request event
    # sequences byte-identical vs lockstep modulo step/lag/wall,
    # syncs{eos} and decode dispatches strictly lower at depth S.
    # Walls (tokens/s, host/dispatch/overlap ms) are report-only --
    from paddle_tpu.observability.flightrec import FlightRecorder
    nd_s = 4                   # the fused window depth under test
    nd_new = int(new_hi)       # long budgets: decode dominates
    nd_prompts = prompts[:num_slots]
    nd_plens = plens[:num_slots]
    # an EOS that really fires mid-stream for request 0 (tokens before
    # EOS are unaffected by the eos config, so picking from the no-EOS
    # reference is exact); other rows run their budgets — the mix of
    # early-EOS and budget finishes is the protocol's whole surface
    nd_ref = np.asarray(model.generate(
        paddle.to_tensor(nd_prompts[0][None, :int(nd_plens[0])]),
        max_new_tokens=nd_new, max_cache_len=cache_len,
        compute_dtype=compute_dtype)._value)[0]
    nd_eos = int(nd_ref[nd_new // 2])

    def _one_depth_trace(depth, lockstep=False):
        reg = MetricsRegistry()
        rec = FlightRecorder()
        # steps_per_call=1 on purpose: block granularity is orthogonal
        # to the depth axis, and at 1 the per-request event stories
        # compare byte-exactly (a stale-active row that finished on
        # device distorts min-budget for a dispatch or two at spc > 1,
        # reordering the n=spc/n=1 choice — token-exact but a
        # different steps-attr sequence)
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=1,
            block_len=pf_block, compute_dtype=compute_dtype,
            eos_token_id=nd_eos, registry=reg, flight_recorder=rec,
            async_dispatch=not lockstep,
            async_depth=1 if lockstep else depth)
        eng.submit(nd_prompts[0][:int(nd_plens[0])],
                   max_new_tokens=steps_per_call + 2)   # warm
        eng.run()
        warm = eng.stats()
        first_real = eng._next_id      # warm requests drop from events
        t0 = time.perf_counter()
        for i in range(num_slots):
            eng.submit(nd_prompts[i][:int(nd_plens[i])],
                       max_new_tokens=nd_new, arrival_time=t0)
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()
        counts = {k: final[k] - warm[k] for k in (
            "block_dispatches", "decode_steps", "async_syncs",
            "async_harvests")}
        counts["eos_syncs"] = (
            final["async_syncs_by_reason"]["eos"]
            - warm["async_syncs_by_reason"]["eos"])
        evs = [e for e in rec.events() if e.request >= first_real]
        admits = [e.request for e in evs if e.kind == "admit"]
        # per-request event stories: step numbering excluded by
        # construction (the tuples carry no step — a fused window
        # compresses steps and stamps events with the dispatch step),
        # wall never recorded in attrs, and the deterministic lag attr
        # stripped; at steps_per_call=1 the remaining CONTENT must
        # match lockstep byte for byte
        stories = {}
        for e in evs:
            stories.setdefault(e.request, []).append(
                (e.kind, tuple(sorted(
                    (k, str(v)) for k, v in e.attrs.items()
                    if k != "lag"))))
        walls = {
            "host_ms": round(reg.get(
                "serving.step.host_seconds").summary()["sum"] * 1e3, 3),
            "dispatch_ms": round(reg.get(
                "serving.step.dispatch_seconds").summary()["sum"]
                * 1e3, 3),
            "overlap_ms": round(reg.get(
                "serving.step.overlap_seconds").summary()["sum"]
                * 1e3, 3),
        }
        depth_hwm = int(reg.get("serving.async.depth").hwm())
        out_toks = np.concatenate([r.output for r in done])
        return (wall, counts, walls, out_toks, admits, stories,
                depth_hwm)

    dl_wall, dl_c, dl_w, dl_out, dl_adm, dl_st, _ = \
        _one_depth_trace(1, lockstep=True)
    d1_wall, d1_c, d1_w, d1_out, d1_adm, d1_st, d1_hwm = \
        _one_depth_trace(1)
    ds_wall, ds_c, ds_w, ds_out, ds_adm, ds_st, ds_hwm = \
        _one_depth_trace(nd_s)
    depth_ab = {
        "depth": nd_s,
        "eos_token_id": nd_eos,
        "tokens_per_s": round(num_slots * nd_new / ds_wall, 1),
        "depth1_tokens_per_s": round(num_slots * nd_new / d1_wall, 1),
        "lockstep_tokens_per_s": round(num_slots * nd_new / dl_wall, 1),
        "eos_syncs": {"depth1": d1_c["eos_syncs"],
                      "depthS": ds_c["eos_syncs"]},
        "block_dispatches": {"lockstep": dl_c["block_dispatches"],
                             "depth1": d1_c["block_dispatches"],
                             "depthS": ds_c["block_dispatches"]},
        "async_harvests": ds_c["async_harvests"],
        "depth_hwm": {"depth1": d1_hwm, "depthS": ds_hwm},
        # wall-shaped step split per arm — reported, never gated
        "host_ms": ds_w["host_ms"],
        "dispatch_ms": ds_w["dispatch_ms"],
        "overlap_ms": ds_w["overlap_ms"],
        "depth1_host_ms": d1_w["host_ms"],
        "lockstep_host_ms": dl_w["host_ms"],
        "gate": {
            "token_exact": bool((ds_out == dl_out).all()
                                and (d1_out == dl_out).all()),
            "eos_syncs_strictly_lower": (
                ds_c["eos_syncs"] < d1_c["eos_syncs"]),
            "dispatches_strictly_lower": (
                ds_c["block_dispatches"] < d1_c["block_dispatches"]),
            "admission_order_identical": (
                ds_adm == dl_adm == d1_adm),
            "event_stories_identical": ds_st == dl_st == d1_st,
            # the depth-1 EOS arm never defers (its hwm stays 0 —
            # exactly the wall this arm exists to show), so only the
            # depth-S pipeline is gated on reaching its configured S
            "depth_gauge_reaches_s": ds_hwm == nd_s and d1_hwm == 0,
        },
    }

    # -- speculative-decoding arm: the SAME engine config with and
    # without per-request spec_decode=K on a repetitive/structured
    # trace (tiled short token patterns — prompt-lookup drafting's home
    # turf: greedy continuations of periodic context are near-periodic,
    # so the n-gram drafter's proposals verify).  SINGLE-STREAM
    # (num_slots=1, steps_per_call=1): speculative decoding trades
    # arithmetic width for sequential depth, so its win lives where
    # forwards are latency-bound — the low-occupancy/interactive
    # regime; at high batch the same slots are better fed by batching
    # (the verify already costs B x width regardless of how many rows
    # drafted).  Decode dominates the budget (long max_new) because
    # spec pays off per decoded token --
    if on_tpu:
        sp_prompt, sp_cache, sp_new, sp_k, sp_n = 128, 512, 96, 6, 8
    else:
        sp_prompt, sp_cache, sp_new, sp_k, sp_n = 24, 128, 96, 6, 6
    # the trace is DEFINED by its output being repetitive (the regime
    # prompt-lookup drafting targets: code, JSON, extraction, copied
    # spans).  Untrained weights produce that regime only from prompts
    # that land in a greedy attractor, so candidates are scored by the
    # draftability of their actual greedy stream (ONE batched
    # generate() + the host-side drafter replayed over it) and the
    # most repetitive sp_n become the trace — the selection criterion
    # IS the trace's stated property, and the acceptance stats below
    # report how repetitive it really was
    from paddle_tpu.inference.speculative import NGramDrafter
    cands = []
    for _ in range(8 * sp_n):
        pat = rng.integers(0, cfg.vocab_size,
                           (int(rng.integers(2, 5)),)).astype(np.int32)
        cands.append(np.tile(pat, sp_prompt // pat.size + 1)[:sp_prompt])
    cand_ids = np.stack(cands)
    streams = np.asarray(model.generate(
        paddle.to_tensor(cand_ids), max_new_tokens=sp_new,
        max_cache_len=sp_cache, compute_dtype=compute_dtype)._value)
    _dr = NGramDrafter()

    def _oracle_iters(prompt_ids, stream):
        """Scheduler iterations a spec engine would take to emit the
        stream (verify advances accepted+1, a draftless step advances
        1) — the drafter replayed over the known greedy output."""
        iters, j = 0, 1
        while j < stream.size:
            d = _dr.propose(
                np.concatenate([prompt_ids, stream[:j]]),
                min(sp_k, stream.size - j))
            iters += 1
            if d.size:
                a = 0
                while a < d.size and j + a < stream.size \
                        and d[a] == stream[j + a]:
                    a += 1
                j += a + 1
            else:
                j += 1
        return iters

    order = np.argsort([_oracle_iters(cand_ids[i], streams[i])
                        for i in range(len(cands))])
    sp_prompts = [cand_ids[i] for i in order[:sp_n]]

    from paddle_tpu.observability import metrics as obs_metrics

    def _accept_hist_buckets():
        h = obs_metrics.get_registry().get("serving.spec.accepted_length")
        if h is None:
            return None, []
        snap = h._snap()["values"].get("")
        return list(h.bounds), (list(snap["buckets"]) if snap else
                                [0] * (len(h.bounds) + 1))

    # the verify only dispatches when something was drafted, and the
    # n-gram drafter may draft nothing over a 4-token warm request —
    # the spec/sampling arms warm with a stub that always proposes,
    # then hand the engine back to the default prompt-lookup drafter
    class _AlwaysDraft:
        def propose(self, context, k):
            return np.repeat(np.asarray(context[-1:], np.int32), k)

    def _goodput_delta(final, warm):
        """The goodput-ledger slice of a stats() delta: all
        DETERMINISTIC token counts (the conservation gate is exact
        integer equality; wall-shaped numbers like TPOT ride the arm
        separately and are never gated)."""
        g = {
            "useful_tokens": final["useful_tokens"]
            - warm["useful_tokens"],
            "wasted_tokens": final["wasted_tokens"]
            - warm["wasted_tokens"],
            "dispatched_tokens": final["dispatched_tokens"]
            - warm["dispatched_tokens"],
            "wasted_by_reason": {
                k: final["wasted_by_reason"][k]
                - warm["wasted_by_reason"][k]
                for k in final["wasted_by_reason"]},
        }
        g["goodput"] = (round(g["useful_tokens"]
                              / g["dispatched_tokens"], 4)
                        if g["dispatched_tokens"] else 0.0)
        g["gate"] = {"conservation_ok":
                     g["useful_tokens"] + g["wasted_tokens"]
                     == g["dispatched_tokens"]}
        return g

    def _mean_tpot_ms(done):
        """Mean per-output-token latency over one arm's finished
        requests — a WALL time: reported for the trajectory, never
        gated (the 2-core CI box's TPOT is jitter, the shape of the
        number is what real accelerators read)."""
        tp = [(r.finish_time - r.first_token_time) / (r.n_emitted - 1)
              for r in done
              if r.state == "finished" and r.first_token_time is not None
              and r.n_emitted > 1]
        return round(1e3 * sum(tp) / len(tp), 3) if tp else None

    def _one_spec_trace(use_spec, sampling_for=lambda i: None):
        # ``sampling_for(i)`` supplies request i's SamplingParams (None
        # = greedy): the spec AND sampling arms share this one trace
        # protocol, so the warm ritual / replay / counter deltas can
        # never drift between them
        # async_dispatch=False on BOTH arms: a spec engine is
        # effectively lockstep anyway (every spec iteration is a
        # forced sync), so a dispatch-ahead no-spec baseline would
        # fold the pipeline's win into this A/B and misattribute it
        # to (against) speculation — the ``async`` sub-object is
        # where the pipeline is measured
        eng = ServingEngine(
            model, num_slots=1, prompt_len=sp_prompt,
            max_cache_len=sp_cache, steps_per_call=1,
            block_len=pf_block, chunk_len=sp_prompt,
            compute_dtype=compute_dtype, async_dispatch=False)
        # warm: chunk prefill, the verify width, AND the plain decode
        # block (the zero-draft fallback path dips into it mid-trace)
        if use_spec:
            eng._drafter = _AlwaysDraft()
        for warm_spec in (sp_k if use_spec else None, None):
            eng.submit(sp_prompts[0], max_new_tokens=4,
                       spec_decode=warm_spec, sampling=sampling_for(0))
        eng.run()
        if use_spec:
            from paddle_tpu.inference.speculative import NGramDrafter
            eng._drafter = NGramDrafter()
        warm = eng.stats()
        _le, h0 = _accept_hist_buckets()
        t0 = time.perf_counter()
        for i, ids in enumerate(sp_prompts):
            eng.submit(ids, max_new_tokens=sp_new, arrival_time=t0,
                       spec_decode=sp_k if use_spec else None,
                       sampling=sampling_for(i))
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()
        le, h1 = _accept_hist_buckets()
        verifies = final["spec_verify_steps"] - warm["spec_verify_steps"]
        drafted = final["spec_draft_tokens"] - warm["spec_draft_tokens"]
        accepted = (final["spec_accepted_tokens"]
                    - warm["spec_accepted_tokens"])
        hits = final["spec_draft_hits"] - warm["spec_draft_hits"]
        misses = final["spec_draft_misses"] - warm["spec_draft_misses"]
        emitted = sp_new * sp_n
        return wall, {
            "mean_accepted_len": round(
                accepted / verifies if verifies else 0.0, 3),
            "acceptance_rate": round(
                accepted / drafted if drafted else 0.0, 4),
            "drafts_per_token": round(drafted / emitted, 4),
            "draft_hit_rate": round(
                hits / (hits + misses) if hits + misses else 0.0, 4),
            "verify_steps": int(verifies),
            "accepted_length_le": le,
            "accepted_length_counts": [int(a - b)
                                       for a, b in zip(h1, h0)],
            "sampled_tokens": final["sampled_tokens"]
            - warm["sampled_tokens"],
            "resamples": final["sample_resamples"]
            - warm["sample_resamples"],
            "goodput": _goodput_delta(final, warm),
            "mean_tpot_ms": _mean_tpot_ms(done),
        }

    def run_spec_arm(use_spec, sampling_for=lambda i: None):
        # best-of-2 walls, same rationale as the prefix arm; counters
        # are deterministic per arm (seeded streams), runs[0] carries
        runs = [_one_spec_trace(use_spec, sampling_for)
                for _ in range(2)]
        wall = min(r[0] for r in runs)
        out = dict(runs[0][1])
        out["tokens_per_s"] = round(float(sp_new * sp_n) / wall, 1)
        return out

    spec_on = run_spec_arm(use_spec=True)
    spec_off = run_spec_arm(use_spec=False)

    # -- sampling arm: the SAME single-stream engine config and
    # draftability-selected trace as the spec arm, run three ways —
    # greedy (the spec arm's no-spec run IS this arm's baseline),
    # stochastically sampled (per-request temperature/top-k +
    # per-request seeds through the slot-indexed PRNG plane), and
    # spec + sampled (stochastic speculative sampling: accept draft i
    # with prob min(1, p_i(d_i)), residual resample on the first cut).
    # The tokens/s deltas price the sampling chain on the decode path;
    # the acceptance-length delta vs the GREEDY spec arm is what
    # temperature does to acceptance economics (the accept test paying
    # p(draft) instead of an argmax match), with the residual-resample
    # count from serving.sample.resamples.  All serving.sample.*
    # deltas also land in the run's ``metrics`` sub-object --
    from paddle_tpu.inference.sampling import SamplingParams
    sa_temp, sa_topk = 0.8, 50

    def _sampling_for(i):
        return SamplingParams(temperature=sa_temp, top_k=sa_topk, seed=i)

    samp_plain = run_spec_arm(use_spec=False, sampling_for=_sampling_for)
    samp_spec = run_spec_arm(use_spec=True, sampling_for=_sampling_for)

    # -- int8 KV-cache arm: the SAME drain trace through two engines
    # that differ ONLY in kv_cache_dtype (int8 codes + f32 absmax
    # scales vs the full-precision cache).  Reported: tokens/s ratio,
    # modeled achieved_GBps per arm (serving.kv.bytes_swept / wall —
    # the arena-sweep roofline basis, which is where the int8 win
    # lives), plus the QUALITY GATE mirroring the weight-int8 gate of
    # _bench_decode: teacher-forced greedy token agreement and NLL
    # delta through the paged cache path (model.verify_step scores a
    # forced stream causally against each arena dtype — every position
    # attends through quantized K/V, so the delta isolates KV
    # quantization error, not weight error) --
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models.generation import (init_paged_kv_arena,
                                              model_arrays, swap_call)

    def _one_kv_trace(kvdt):
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=steps_per_call,
            block_len=pf_block, compute_dtype=compute_dtype,
            kv_cache_dtype=kvdt)
        for _ in range(2):     # warm chunk program + both block sizes
            eng.submit(prompts[0][:int(plens[0])],
                       max_new_tokens=steps_per_call + 2)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        for i in range(n_requests):
            eng.submit(prompts[i][:int(plens[i])],
                       max_new_tokens=int(news[i]), arrival_time=t0)
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()
        swept = final["kv_bytes_swept"] - warm["kv_bytes_swept"]
        return wall, swept, np.concatenate([r.output for r in done])

    def run_kv_arm(kvdt):
        # best-of-2 walls; the swept-bytes model and outputs are
        # deterministic per arm, so runs[0] carries them
        runs = [_one_kv_trace(kvdt) for _ in range(2)]
        wall = min(r[0] for r in runs)
        return wall, runs[0][1], runs[0][2]

    kv_base_wall, kv_base_swept, kv_base_out = run_kv_arm(None)
    kv_q_wall, kv_q_swept, kv_q_out = run_kv_arm("int8")

    # teacher-forced gate stream: request 0's prompt + the BASELINE
    # engine's own greedy continuation — the trace's actual token
    # distribution, scored position-by-position so one near-tie flip
    # cannot cascade (free-running agreement is reported separately)
    n0 = int(plens[0])
    tf_stream = np.concatenate(
        [prompts[0][:n0], kv_base_out[:int(news[0])]]).astype(np.int32)
    tf_t = int(tf_stream.size)
    n_layers, hkv_s, d_s = model.kv_cache_spec()
    tf_mb = -(-tf_t // pf_block)
    tf_tables = jnp.arange(tf_mb, dtype=jnp.int32)[None, :]
    params, buffers = model_arrays(model)

    def _kv_forced(kvdt):
        adt = jnp.dtype(kvdt if kvdt else compute_dtype)

        def pure(p_values, b_values, toks):
            def run():
                arenas = init_paged_kv_arena(
                    n_layers, tf_mb, pf_block, hkv_s, d_s, adt)
                kvs = [tuple(e) + (tf_tables,) for e in arenas]
                logits, _ = model.verify_step(
                    toks, jnp.zeros((1,), jnp.int32),
                    jnp.full((1,), tf_t, jnp.int32), kvs)
                lp = jax.nn.log_softmax(
                    logits[:, :-1].astype(jnp.float32), -1)
                nll = -jnp.take_along_axis(
                    lp, toks[:, 1:][..., None].astype(jnp.int32),
                    -1).mean()
                return nll, jnp.argmax(logits, -1).astype(jnp.int32)
            return swap_call(params, buffers, p_values, b_values,
                             compute_dtype, run)
        nll, am = jax.jit(pure)(
            [p._value for p in params], [bf._value for bf in buffers],
            jnp.asarray(tf_stream[None, :]))
        return float(nll), np.asarray(am)

    nll_base, am_base = _kv_forced(None)
    nll_q, am_q = _kv_forced("int8")
    tf_agree = float((am_base == am_q).mean())
    delta_nll_pct = 100.0 * (nll_q - nll_base) / abs(nll_base)
    # baseline_* keys: the full-precision arm runs in compute_dtype
    # (bf16 on TPU, f32 on CPU — baseline_dtype says which), so a
    # dtype-named key would misread across platforms
    kv_int8 = {
        "baseline_dtype": compute_dtype,
        "tokens_per_s": round(float(news.sum()) / kv_q_wall, 1),
        "baseline_tokens_per_s": round(
            float(news.sum()) / kv_base_wall, 1),
        "vs_baseline": round(kv_base_wall / max(kv_q_wall, 1e-9), 3),
        "achieved_GBps": round(kv_q_swept / kv_q_wall / 1e9, 3),
        "baseline_achieved_GBps": round(
            kv_base_swept / kv_base_wall / 1e9, 3),
        "kv_bytes_swept": int(kv_q_swept),
        "baseline_kv_bytes_swept": int(kv_base_swept),
        "token_agreement": round(tf_agree, 4),
        "engine_token_agreement": round(
            float((kv_base_out == kv_q_out).mean()), 4),
        "delta_nll_pct": round(delta_nll_pct, 4),
        "forced_tokens": tf_t,
        "gate": {"token_agreement_ok": tf_agree >= 0.98,
                 "nll_ok": abs(delta_nll_pct) <= 1.0},
    }

    # -- weight-quant arm: the SAME drain trace through three engines
    # that differ ONLY in weight_dtype (bf16/f32 baseline vs int8 vs
    # int4 code planes + per-output-channel f32 scales).  tokens/s is
    # REPORT-ONLY — on CPU the XLA dequant-view fallback serves the
    # quantized arms, so wall clock says nothing about the TPU kernel.
    # Gates are deterministic counters only: the teacher-forced quality
    # gate per quantized dtype (same forced stream, tables and scoring
    # as the kv_int8 gate — here the KV arena stays full-precision so
    # the delta isolates WEIGHT quantization error), the modeled weight
    # sweep strictly decreasing baseline > int8 > int4, equal decode
    # dispatch counts (scheduling identity), and the route-counter
    # proof that 128-aligned decode shapes dispatch the Pallas kernel
    # (interpret mode) for both bit widths --
    from paddle_tpu.inference.llm import _param_swapper
    from paddle_tpu.observability.metrics import get_registry
    from paddle_tpu.ops.pallas import quantized_matmul as qmm_mod

    def _wq_forced(eng):
        wp = _param_swapper(model, eng.cfg, wq=eng._wq)

        def pure(pb_values, toks):
            def run():
                arenas = init_paged_kv_arena(
                    n_layers, tf_mb, pf_block, hkv_s, d_s,
                    jnp.dtype(compute_dtype))
                kvs = [tuple(e) + (tf_tables,) for e in arenas]
                logits, _ = model.verify_step(
                    toks, jnp.zeros((1,), jnp.int32),
                    jnp.full((1,), tf_t, jnp.int32), kvs)
                lp = jax.nn.log_softmax(
                    logits[:, :-1].astype(jnp.float32), -1)
                nll = -jnp.take_along_axis(
                    lp, toks[:, 1:][..., None].astype(jnp.int32),
                    -1).mean()
                top2 = jax.lax.top_k(
                    logits.astype(jnp.float32), 2)[0]
                return (nll, jnp.argmax(logits, -1).astype(jnp.int32),
                        top2[..., 0] - top2[..., 1])
            return wp(pb_values, run)
        nll, am, margin = jax.jit(pure)(
            eng._pb, jnp.asarray(tf_stream[None, :]))
        return float(nll), np.asarray(am), np.asarray(margin)

    def _one_wq_trace(wdt):
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=steps_per_call,
            block_len=pf_block, compute_dtype=compute_dtype,
            weight_dtype=wdt)
        for _ in range(2):     # warm chunk program + both block sizes
            eng.submit(prompts[0][:int(plens[0])],
                       max_new_tokens=steps_per_call + 2)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        for i in range(n_requests):
            eng.submit(prompts[i][:int(plens[i])],
                       max_new_tokens=int(news[i]), arrival_time=t0)
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()
        nll, am, margin = _wq_forced(eng)
        return {
            "wall": wall,
            "swept": final["weight_bytes_swept"]
            - warm["weight_bytes_swept"],
            "dispatches": final["block_dispatches"]
            - warm["block_dispatches"],
            "out": np.concatenate([r.output for r in done]),
            "nll": nll, "am": am, "margin": margin,
        }

    wq_base = _one_wq_trace(None)
    wq_q = {wdt: _one_wq_trace(wdt) for wdt in ("int8", "int4")}

    # the token gate scores DECISIVE positions only: where the
    # baseline's own top-2 logit margin clears 0.01 (f32 noise is
    # ~1e-6, typical margins ~0.1; ~93% of positions are decisive on
    # the CPU bench model).  Below that the baseline is calling a
    # coin flip and a quantized flip is a tie-break census entry, not
    # a quality signal — int8's only disagreements sit at margins
    # < 1e-3 with |dlogit| < 0.03
    wq_decisive = wq_base["margin"] > 0.01

    def _wq_report(arm):
        agree = float((wq_base["am"] == arm["am"]).mean())
        agree_dec = float(
            (wq_base["am"] == arm["am"])[wq_decisive].mean())
        dnll = 100.0 * (arm["nll"] - wq_base["nll"]) \
            / abs(wq_base["nll"])
        return {
            "tokens_per_s": round(float(news.sum()) / arm["wall"], 1),
            "achieved_GBps": round(
                arm["swept"] / arm["wall"] / 1e9, 3),
            "weight_bytes_swept": int(arm["swept"]),
            "token_agreement": round(agree, 4),
            "decisive_token_agreement": round(agree_dec, 4),
            "engine_token_agreement": round(
                float((wq_base["out"] == arm["out"]).mean()), 4),
            "delta_nll_pct": round(dnll, 4),
            "token_agreement_ok": agree_dec >= 0.98,
            "nll_ok": abs(dnll) <= 1.0,
        }

    wq_rep = {wdt: _wq_report(arm) for wdt, arm in wq_q.items()}
    # gate split by bit width: int8 holds the strict kv_int8-style
    # token gate (decisive agreement >= 0.98 AND |dNLL| <= 1%); int4
    # gates on dNLL only with agreement REPORT-ONLY — at 4 bits the
    # weight perturbation (mean |dlogit| ~0.09) overlaps the margin
    # distribution itself, flipping genuinely-decided argmaxes
    # (measured dNLL ~0.2% with agreement ~0.6 on the CPU bench
    # model); NLL is the distribution-level gate

    # route-counter proof: 128-aligned decode shapes really dispatch
    # the Pallas kernel (interpret mode off-TPU) for both bit widths,
    # kernel output matching the XLA dequant fallback — the enablement
    # probe is forced so the proof runs identically on CPU and TPU
    route = get_registry().counter("pallas.quantized_matmul.route",
                                   labels=("decision", "reason"))
    wq_rng = np.random.default_rng(29)
    rx = jnp.asarray(wq_rng.standard_normal((8, 128)), jnp.float32)
    rw8 = jnp.asarray(wq_rng.integers(-127, 128, (128, 128)), jnp.int8)
    rsc = jnp.asarray(wq_rng.uniform(0.01, 0.02, (128,)), jnp.float32)
    rw4 = qmm_mod.pack_int4(
        jnp.asarray(wq_rng.integers(-7, 8, (128, 128)), jnp.int8))
    b8 = route.value(decision="pallas", reason="int8_ok")
    b4 = route.value(decision="pallas", reason="int4_ok")
    _saved_enabled = qmm_mod.pallas_enabled
    try:
        qmm_mod.pallas_enabled = lambda: True
        r_out8 = qmm_mod.routed_quantized_matmul(rx, rw8, rsc)
        r_out4 = qmm_mod.routed_quantized_matmul(rx, rw4, rsc, bits=4)
    finally:
        qmm_mod.pallas_enabled = _saved_enabled
    route_ok = bool(
        route.value(decision="pallas", reason="int8_ok") == b8 + 1
        and route.value(decision="pallas", reason="int4_ok") == b4 + 1
        and np.allclose(np.asarray(r_out8),
                        np.asarray(qmm_mod.dequant_matmul_xla(
                            rx, rw8, rsc)), atol=1e-4, rtol=1e-4)
        and np.allclose(np.asarray(r_out4),
                        np.asarray(qmm_mod.dequant_matmul_xla(
                            rx, rw4, rsc, bits=4)), atol=1e-4,
                        rtol=1e-4))

    weight_quant = {
        "baseline_dtype": compute_dtype,
        "baseline_tokens_per_s": round(
            float(news.sum()) / wq_base["wall"], 1),
        "baseline_achieved_GBps": round(
            wq_base["swept"] / wq_base["wall"] / 1e9, 3),
        "baseline_weight_bytes_swept": int(wq_base["swept"]),
        "forced_tokens": tf_t,
        "decisive_frac": round(float(wq_decisive.mean()), 4),
        "int8": wq_rep["int8"],
        "int4": wq_rep["int4"],
        "gate": {
            "token_agreement_ok": bool(
                wq_rep["int8"]["token_agreement_ok"]),
            "nll_ok": bool(wq_rep["int8"]["nll_ok"]
                           and wq_rep["int4"]["nll_ok"]),
            "bytes_order_ok": bool(
                wq_base["swept"] > wq_q["int8"]["swept"]
                > wq_q["int4"]["swept"] > 0),
            "dispatch_parity_ok": bool(
                wq_base["dispatches"] == wq_q["int8"]["dispatches"]
                == wq_q["int4"]["dispatches"]),
            "route_ok": route_ok,
        },
    }

    # -- overload arm: a bursty trace that oversubscribes the BLOCK
    # POOL (two long low-priority background requests pin nearly every
    # block, then a burst of short high-priority interactive requests
    # arrives) runs with preemption ON vs OFF.  With preemption the
    # scheduler swaps a long victim's KV to the host-RAM tier and
    # serves the burst; without it the burst queues behind the
    # long-tail requests.  Reported: p99 TTFT of the interactive class
    # (no-SLO replay, everything completes, the delta is pure queueing)
    # and completion rate under a queue-delay SLO calibrated between
    # the two arms' TTFTs (replayed with max_queue_delay_s, the
    # no-preempt arm sheds-by-timeout what it cannot serve in time).
    # The serving.preempt.*/swap.*/shed.*/timeout.* registry deltas
    # land in the run's ``metrics`` sub-object like every other
    # instrument this section fires --
    from paddle_tpu.inference import AdmissionError, FaultInjector

    if on_tpu:
        ov_prompt, ov_block, ov_cache = 64, 16, 256
        ov_long_new, ov_short_new, ov_n_short = 192, 16, 6
    else:
        ov_prompt, ov_block, ov_cache = 16, 8, 80
        ov_long_new, ov_short_new, ov_n_short = 64, 8, 6
    ov_plen = 12                       # both classes' prompt length
    long_blocks = -(-(ov_plen + ov_long_new - 1) // ov_block)
    short_blocks = -(-(ov_plen + ov_short_new - 1) // ov_block)
    # two longs pin all but (short_blocks - 1) blocks: a short can
    # never be admitted beside them without preemption
    ov_blocks = 2 * long_blocks + short_blocks - 1
    ov_long_ids = [rng.integers(0, cfg.vocab_size,
                                ov_plen).astype(np.int32)
                   for _ in range(2)]
    ov_short_ids = [rng.integers(0, cfg.vocab_size,
                                 ov_plen).astype(np.int32)
                    for _ in range(ov_n_short)]

    def _one_overload_trace(preempt, short_delay):
        fi = FaultInjector()
        eng = ServingEngine(
            model, num_slots=3, prompt_len=ov_prompt,
            max_cache_len=ov_cache, steps_per_call=steps_per_call,
            block_len=ov_block, num_blocks=ov_blocks,
            compute_dtype=compute_dtype, enable_preemption=preempt,
            fault_injector=fi)
        # warm chunk + both decode block sizes + the swap-out gather /
        # swap-in scatter programs (a forced round-trip outside the
        # timed window, identical ritual in both arms)
        wr = eng.submit(ov_long_ids[0],
                        max_new_tokens=steps_per_call + 2)
        eng.step()
        fi.force_swap(wr.request_id)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        longs = [eng.submit(ids, max_new_tokens=ov_long_new,
                            arrival_time=t0, priority=0)
                 for ids in ov_long_ids]
        # the longs must be ADMITTED (holding the pool) before the
        # burst arrives — that is the overload scenario; two steps run
        # both prefill chunks, then the interactive burst lands on a
        # pinned pool and only preemption can serve it promptly
        eng.step()
        eng.step()
        shorts = [eng.submit(ids, max_new_tokens=ov_short_new,
                             priority=1, max_queue_delay_s=short_delay)
                  for ids in ov_short_ids]
        eng.run()
        final = eng.stats()
        served = [r for r in longs + shorts if r.state == "finished"]
        ttfts = sorted(r.ttft for r in shorts if r.ttft is not None)
        return {
            "short_ttfts": ttfts,
            "completion_rate": len(served) / (2 + ov_n_short),
            "timeouts": final["timeouts"] - warm["timeouts"],
            "preemptions": final["preemptions"] - warm["preemptions"],
            "swap_blocks_out": final["swap_blocks_out"]
            - warm["swap_blocks_out"],
            "goodput": _goodput_delta(final, warm),
            "slo_attained": final["slo_attained"] - warm["slo_attained"],
            "slo_missed": final["slo_missed"] - warm["slo_missed"],
            "mean_tpot_ms": _mean_tpot_ms(longs + shorts),
        }

    # phase 1 (no SLO): the pure-queueing p99 TTFT delta
    ov_on = _one_overload_trace(preempt=True, short_delay=None)
    ov_off = _one_overload_trace(preempt=False, short_delay=None)
    on_p99 = ov_on["short_ttfts"][-1] if ov_on["short_ttfts"] else 0.0
    off_p99 = ov_off["short_ttfts"][-1] if ov_off["short_ttfts"] else 0.0
    # phase 2 (queue-delay SLO calibrated BETWEEN the arms — the
    # geometric mean of the preempt arm's p99 and the no-preempt arm's
    # fastest short admission, i.e. an SLO the preempt arm meets and
    # the no-preempt arm cannot): the completion-rate delta
    off_min = (ov_off["short_ttfts"][0]
               if ov_off["short_ttfts"] else 0.1)
    ov_delay = float(np.sqrt(max(on_p99, 1e-6) * max(off_min, 1e-6)))
    ov_on_slo = _one_overload_trace(preempt=True, short_delay=ov_delay)
    ov_off_slo = _one_overload_trace(preempt=False,
                                     short_delay=ov_delay)

    # bounded-queue shed micro-demo (pure host admission, no compute):
    # a full queue rejects an equal-class arrival with AdmissionError
    # and evicts a lower-class request for a higher-class one
    shed_eng = ServingEngine(
        model, num_slots=1, prompt_len=ov_prompt,
        max_cache_len=ov_cache, block_len=ov_block,
        compute_dtype=compute_dtype, max_queue=2)
    far = time.perf_counter() + 1e6
    shed_eng.submit(ov_short_ids[0], max_new_tokens=2,
                    arrival_time=far, priority=1)
    low = shed_eng.submit(ov_short_ids[1], max_new_tokens=2,
                          arrival_time=far, priority=0)
    shed_rejected = 0
    try:
        shed_eng.submit(ov_short_ids[2], max_new_tokens=2,
                        arrival_time=far, priority=0)
    except AdmissionError:
        shed_rejected = 1
    shed_eng.submit(ov_short_ids[3], max_new_tokens=2,
                    arrival_time=far, priority=2)   # evicts `low`
    shed_evicted = int(low.state == "shed")

    overload = {
        "n_long": 2, "n_short": ov_n_short,
        "long_new": ov_long_new, "short_new": ov_short_new,
        "num_blocks": ov_blocks,
        "p99_ttft_ms": round(on_p99 * 1e3, 1),
        "no_preempt_p99_ttft_ms": round(off_p99 * 1e3, 1),
        "ttft_vs_no_preempt": round(off_p99 / max(on_p99, 1e-9), 3),
        "preemptions": ov_on["preemptions"],
        "swap_blocks_out": ov_on["swap_blocks_out"],
        "short_delay_slo_ms": round(ov_delay * 1e3, 1),
        "completion_rate": ov_on_slo["completion_rate"],
        "no_preempt_completion_rate": ov_off_slo["completion_rate"],
        "slo_timeouts": ov_on_slo["timeouts"],
        "no_preempt_slo_timeouts": ov_off_slo["timeouts"],
        "shed_demo": {"rejected": shed_rejected,
                      "evicted": shed_evicted},
        # goodput ledger (no-SLO replay: every count deterministic —
        # the conservation gate inside is exact integer equality);
        # no_preempt_goodput shows what preemption costs in useful
        # fraction — exact-bytes swap keeps recompute_preempt at 0,
        # so the arms differ only via scheduling shape
        "goodput": ov_on["goodput"],
        "no_preempt_goodput": ov_off["goodput"]["goodput"],
        # SLO attainment + TPOT are WALL-shaped (the timeout sweep is
        # clock-driven): reported for the trajectory, never gated
        "slo_attained": ov_on_slo["slo_attained"],
        "slo_missed": ov_on_slo["slo_missed"],
        "no_preempt_slo_attained": ov_off_slo["slo_attained"],
        "no_preempt_slo_missed": ov_off_slo["slo_missed"],
        "mean_tpot_ms": ov_on["mean_tpot_ms"],
    }

    # -- multi-tenant LoRA arm (``lora`` sub-object): tokens/s vs
    # adapter count (K = 1/4/8 variants round-robined over a fixed
    # batch — the S-LoRA claim is K-adapter serving staying near the
    # K=1 rate) plus the two-tenant starvation trace FIFO vs
    # fair-share.  Gates are DETERMINISTIC counters only: K=1 batched
    # output token-exact vs merged-weights generate(), gather count ==
    # dispatch count (every dispatch carried adapter rows), fair-share
    # admission reorders > 0 with the steady tenant's completion count
    # strictly improving at a fixed step budget; walls and p99 TTFT
    # ride along report-only --
    from paddle_tpu.inference.lora import AdapterStore, LoraAdapter
    from paddle_tpu.models.lora import merged_adapter
    lo_new = steps_per_call + 2
    lo_n = 12
    lo_prompts = [rng.integers(0, cfg.vocab_size,
                               (prompt,)).astype(np.int32)
                  for _ in range(lo_n)]

    def _one_lora_trace(k_adapters):
        reg = obs_metrics.MetricsRegistry()
        store = AdapterStore(model, slots=max(k_adapters, 1),
                             max_rank=4, dtype=compute_dtype,
                             registry=reg)
        ads = [LoraAdapter.random(cfg, f"ad{j}", rank=4, seed=100 + j,
                                  scale=0.05)
               for j in range(k_adapters)]
        for ad in ads:
            store.register(ad)
        eng = ServingEngine(
            model, num_slots=num_slots, prompt_len=prompt,
            max_cache_len=cache_len, steps_per_call=steps_per_call,
            compute_dtype=compute_dtype, adapter_store=store,
            registry=reg)
        # warm both block sizes + the chunk program (lora variants)
        for _ in range(2):
            eng.submit(lo_prompts[0], max_new_tokens=lo_new,
                       adapter=ads[0].name)
        eng.run()
        warm = eng.stats()
        t0 = time.perf_counter()
        reqs = [eng.submit(lo_prompts[i], max_new_tokens=lo_new,
                           adapter=ads[i % k_adapters].name,
                           arrival_time=t0)
                for i in range(lo_n)]
        done = eng.run()
        wall = max(r.finish_time for r in done) - t0
        final = eng.stats()
        dispatches = (final["prefill_chunks"] - warm["prefill_chunks"]
                      + final["block_dispatches"]
                      - warm["block_dispatches"])
        gathers = final["lora_dispatches"] - warm["lora_dispatches"]
        return {
            "tokens_per_s": round(lo_n * lo_new / wall, 1),
            "gathers": int(gathers),
            "swap_ins": int(
                reg.get("serving.lora.swap_ins").value()),
            # every dispatch of this all-adapter trace rode the
            # gathered-einsum path — a deterministic route gate
            "gate_gather_count": bool(gathers == dispatches > 0),
        }, reqs, ads

    lora_arms = {k: _one_lora_trace(k)[0] for k in (4, 8)}
    k1, k1_reqs, k1_ads = _one_lora_trace(1)
    # K=1 parity gate: the batched gathered path reproduces the
    # merged-weights per-request oracle token-for-token
    with merged_adapter(model, k1_ads[0]):
        want = np.asarray(model.generate(
            paddle.to_tensor(lo_prompts[0][None, :].astype(np.int32)),
            max_new_tokens=lo_new, max_cache_len=cache_len,
            compute_dtype=compute_dtype)._value)[0]
    k1["gate_k1_token_exact"] = bool(
        np.array_equal(k1_reqs[0].output, want))
    lora_arms[1] = k1

    # two-tenant starvation trace: 6 bursty + 3 steady requests at
    # t=0 through a 1-slot engine, FIFO (one shared tenant) vs
    # fair-share (two tenants), fixed step budget
    st_prompts = [rng.integers(0, cfg.vocab_size,
                               (max(4, prompt // 4),)).astype(np.int32)
                  for _ in range(9)]
    # budget the steps so FIFO is still inside the burst when the
    # window closes (each 3-token request spans ~2-3 scheduler steps
    # on the 1-slot engine, so the 6-request burst alone eats ~12+)
    st_steps = 12

    def _one_starvation(tenants):
        eng = ServingEngine(
            model, num_slots=1, prompt_len=st_prompts[0].size,
            max_cache_len=st_prompts[0].size + 8, steps_per_call=1,
            compute_dtype=compute_dtype,
            registry=obs_metrics.MetricsRegistry())
        reqs = [eng.submit(st_prompts[i], max_new_tokens=3, tenant=t)
                for i, t in enumerate(tenants)]
        for _ in range(st_steps):
            eng.step()
        steady = [r for i, r in enumerate(reqs) if i >= 6]
        fin = sum(r.state == "finished" for r in steady)
        ttfts = sorted(r.ttft for r in steady if r.ttft is not None)
        p99 = (round(1e3 * ttfts[min(len(ttfts) - 1, int(
            0.99 * len(ttfts)))], 1) if ttfts else None)
        return fin, p99, eng.stats()["fair_reorders"]

    fifo_fin, fifo_p99, _r0 = _one_starvation(["default"] * 9)
    fair_fin, fair_p99, fair_reorders = _one_starvation(
        ["bursty"] * 6 + ["steady"] * 3)
    lora = {
        "adapters": lora_arms,
        "k8_vs_k1": round(
            lora_arms[8]["tokens_per_s"]
            / max(lora_arms[1]["tokens_per_s"], 1e-9), 3),
        "starvation": {
            "steps": st_steps,
            "fifo_steady_finished": int(fifo_fin),
            "fair_steady_finished": int(fair_fin),
            "fair_reorders": int(fair_reorders),
            # deterministic gates: fairness reordered the queue and
            # the steady tenant strictly gained completions
            "gate_steady_improves": bool(fair_fin > fifo_fin),
            "gate_reordered": bool(fair_reorders > 0),
            # p99 TTFT of the steady tenant is WALL — report-only
            "fifo_steady_p99_ttft_ms": fifo_p99,
            "fair_steady_p99_ttft_ms": fair_p99,
        },
    }

    # -- front-door router arm (``router`` sub-object): the SAME
    # multi-turn conversation trace with one LoRA adapter per
    # conversation through a 2-replica Router, affinity vs
    # round-robin.  Affinity keeps each conversation on the replica
    # that holds its history (radix tree) and its adapter (HBM arena);
    # round-robin alternates replicas every turn, so the same trace
    # pays prefix recomputes and adapter swap-ins instead.  Outputs
    # depend only on (prompt, adapter) — greedy, identical weights on
    # both replicas — so the traces are engine-identical across arms
    # and every gate below is a deterministic counter --
    from paddle_tpu.inference.router import Router

    rt_turns, rt_convs, rt_user = 3, 3, 6
    rt_ads = [LoraAdapter.random(cfg, f"rt_a{j}", rank=4,
                                 seed=300 + j, scale=0.05)
              for j in range(rt_convs)]

    def _one_router_trace(affinity):
        engs, eng_regs = [], []
        for _ei in range(2):
            reg = obs_metrics.MetricsRegistry()
            store = AdapterStore(model, slots=2, max_rank=4,
                                 dtype=compute_dtype, registry=reg)
            for ad in rt_ads:
                store.register(ad)
            eng = ServingEngine(
                model, num_slots=2, prompt_len=tr_prompt,
                max_cache_len=tr_cache, steps_per_call=steps_per_call,
                block_len=tr_block, chunk_len=tr_chunk,
                num_blocks=tr_blocks,
                host_cache_blocks=8 * tr_blocks,
                compute_dtype=compute_dtype, adapter_store=store,
                registry=reg)
            # warm the LoRA chunk + both block-size programs outside
            # the timed/counted window (identical ritual per replica)
            for _ in range(2):
                eng.submit(tr_sys_ids,
                           max_new_tokens=steps_per_call + 2,
                           adapter=rt_ads[0].name)
            eng.run()
            engs.append(eng)
            eng_regs.append(reg)
        router = Router(engs, affinity=affinity,
                        registry=obs_metrics.MetricsRegistry())
        warm_hits = sum(e.stats()["prefix_hit_tokens"] for e in engs)
        warm_swaps = sum(r.get("serving.lora.swap_ins").value()
                         for r in eng_regs)
        rrng = np.random.default_rng(11)    # identical trace per arm
        hist = [list(tr_sys_ids) for _ in range(rt_convs)]
        outs = {ci: [] for ci in range(rt_convs)}
        toks = 0
        t0 = time.perf_counter()
        for _turn in range(rt_turns):
            reqs = []
            for ci in range(rt_convs):
                user = rrng.integers(0, cfg.vocab_size,
                                     rt_user).astype(np.int32)
                hist[ci].extend(int(x) for x in user)
                ids = np.asarray(hist[ci], np.int32)
                reqs.append((ci, router.submit(
                    ids, max_new_tokens=tr_new,
                    adapter=rt_ads[ci].name)))
            router.run(wall_timeout_s=600)
            for ci, h in reqs:
                out = h.output
                hist[ci].extend(int(x) for x in out)
                outs[ci].append(np.asarray(out))
                toks += out.size
        wall = time.perf_counter() - t0
        rs = router.stats()
        return {
            "tokens_per_s": round(toks / wall, 1),
            "prefix_hit_tokens": int(
                sum(e.stats()["prefix_hit_tokens"] for e in engs)
                - warm_hits),
            "adapter_swap_ins": int(
                sum(r.get("serving.lora.swap_ins").value()
                    for r in eng_regs) - warm_swaps),
            "routed_by_reason": rs["routed_by_reason"],
            "prefix_affinity_tokens": rs["prefix_affinity_tokens"],
            "adapter_affinity_hits": rs["adapter_affinity_hits"],
        }, outs

    rt_aff, rt_aff_outs = _one_router_trace(affinity=True)
    rt_rr, rt_rr_outs = _one_router_trace(affinity=False)
    router_ab = {
        "replicas": 2, "turns": rt_turns,
        "conversations": rt_convs, "adapters": rt_convs,
        "affinity": rt_aff,
        "round_robin": rt_rr,
        "hit_tokens_vs_round_robin": round(
            rt_aff["prefix_hit_tokens"]
            / max(rt_rr["prefix_hit_tokens"], 1), 3),
        # deterministic gates (the acceptance criteria): identical
        # per-request outputs across arms, strictly more cache hit
        # tokens and strictly fewer adapter swap-ins under affinity
        "gate_token_exact": bool(all(
            np.array_equal(a, b)
            for ci in range(rt_convs)
            for a, b in zip(rt_aff_outs[ci], rt_rr_outs[ci]))),
        "gate_prefix_hits_higher": bool(
            rt_aff["prefix_hit_tokens"] > rt_rr["prefix_hit_tokens"]),
        "gate_swap_ins_lower": bool(
            rt_aff["adapter_swap_ins"] < rt_rr["adapter_swap_ins"]),
    }

    # -- replica failover arm (``failover`` sub-object): a seeded
    # kill-at-step trace through a 2-replica router — one request
    # force-swapped to the host tier (its parcel is what migrates at
    # exact bytes), then its replica killed mid-flight — failover ON
    # vs OFF.  Gated ONLY on deterministic counters: the ON arm
    # completes every request token-for-token equal to the no-fault
    # reference (completion 1.0), the OFF kill-switch arm loses the
    # victim's requests (completion < 1.0, typed terminal 'failed'),
    # and the migrated-block / failover-path counts are exact.
    # Walls are report-only per the bench-gate discipline --
    from paddle_tpu.inference import FaultInjector

    fo_rng = np.random.default_rng(23)
    fo_prompts = [fo_rng.integers(0, cfg.vocab_size,
                                  (int(n),)).astype(np.int32)
                  for n in fo_rng.integers(tr_user, 3 * tr_user, 4)]
    # long enough that the kill lands mid-decode (the fault schedule
    # below swaps + kills ~4 scheduler steps in)
    fo_new = 4 * tr_new

    def _one_failover_trace(failover_on, inject):
        engs, injs = [], []
        for _ in range(2):
            inj = FaultInjector() if inject else None
            engs.append(ServingEngine(
                model, num_slots=2, prompt_len=tr_prompt,
                max_cache_len=tr_cache, steps_per_call=steps_per_call,
                block_len=tr_block, chunk_len=tr_chunk,
                num_blocks=tr_blocks, compute_dtype=compute_dtype,
                registry=obs_metrics.MetricsRegistry(),
                fault_injector=inj))
            injs.append(inj)
        rt = Router(engs, failover=failover_on,
                    registry=obs_metrics.MetricsRegistry())
        t0 = time.perf_counter()
        hs = [rt.submit(p, max_new_tokens=fo_new, arrival_time=0.0)
              for p in fo_prompts]
        rt.step(now=0.0)                  # routes everything
        affected = 0
        victim_blocks = 0
        if inject:
            for _ in range(2):
                rt.step(now=0.0)
            vi = hs[0].engine
            # park the streamed-ahead request on the swap list (the
            # armed alloc failures block its resume), then kill
            injs[vi].force_swap(hs[0].request_id)
            injs[vi].fail_allocs(None)
            rt.step(now=0.0)
            victim_blocks = (hs[0]._req.swap.n_blocks
                             if hs[0].state == "swapped" else 0)
            affected = sum(
                1 for h in hs if h.engine == vi
                and h.state not in ("finished", "failed"))
            injs[vi].kill_at_step(engs[vi]._step_idx + 1)
        steps = 0
        while any(h.state not in ("finished", "failed", "timeout",
                                  "shed", "cancelled") for h in hs):
            rt.step(now=0.0)
            steps += 1
            if steps > 400:
                break
        wall = time.perf_counter() - t0
        outs = [np.asarray(h.output) for h in hs]
        done = sum(h.state == "finished" for h in hs)
        rs = rt.stats()
        return {
            "completion_rate": round(done / len(hs), 3),
            "failed": rs["failed"],
            "replica_faults": rs["replica_faults"],
            "failover_requests": rs["failover_requests"],
            "migrated_blocks": rs["migrated_blocks"],
            "migrated_bytes": rs["migrated_bytes"],
            "wall_ms": round(1e3 * wall, 1),
        }, outs, affected, victim_blocks

    fo_ref, fo_ref_outs, _a0, _v0 = _one_failover_trace(
        True, inject=False)
    fo_on, fo_on_outs, fo_affected, fo_vblocks = _one_failover_trace(
        True, inject=True)
    fo_off, fo_off_outs, _a1, _v1 = _one_failover_trace(
        False, inject=True)
    failover_ab = {
        "replicas": 2, "n_requests": len(fo_prompts),
        "max_new": fo_new,
        "reference": fo_ref, "on": fo_on, "off": fo_off,
        "affected_requests": int(fo_affected),
        "victim_parcel_blocks": int(fo_vblocks),
        # deterministic gates: failover recovers EVERYTHING the fault
        # touched, token-for-token; the kill-switch arm provably loses
        # requests; migration moved exactly the victim's resident
        # parcel; every affected request cost exactly one retry
        "gate_on_token_exact": bool(all(
            np.array_equal(a, b)
            for a, b in zip(fo_ref_outs, fo_on_outs))),
        "gate_on_completes_all": bool(
            fo_on["completion_rate"] == 1.0 and fo_on["failed"] == 0),
        "gate_off_loses_requests": bool(
            fo_off["completion_rate"] < 1.0 and fo_off["failed"] > 0),
        "gate_migrated_blocks_exact": bool(
            fo_on["migrated_blocks"] == fo_vblocks and fo_vblocks > 0),
        "gate_retries_exact": bool(
            fo_on["failover_requests"] == fo_affected),
    }

    # -- fleet observability arm (``fleet_obs`` sub-object, PR 17):
    # the SAME seeded kill trace re-run with the whole observability
    # plane attached — per-replica flight recorders, the router's
    # recorder, the SLO burn-rate monitor, a step-indexed time-series.
    # Gated ONLY on deterministic facts: the stitched fleet record
    # accounts for every ring event, explain() renders the one migrate
    # hop at the exact block count, the monitor alerts exactly once on
    # the kill, and the instrumented run stays token-for-token equal
    # to the uninstrumented ON arm (observability must not perturb the
    # trace).  Sampling overhead is the PR-2 disabled-mode micro-bench
    # — walls report-only --
    from paddle_tpu.observability.fleet import SLOBurnRateMonitor
    from paddle_tpu.observability.flightrec import FlightRecorder
    from paddle_tpu.observability.timeseries import TimeSeriesRecorder

    def _one_obs_trace():
        engs, injs, recs = [], [], []
        for _ in range(2):
            inj = FaultInjector()
            rec = FlightRecorder()
            engs.append(ServingEngine(
                model, num_slots=2, prompt_len=tr_prompt,
                max_cache_len=tr_cache, steps_per_call=steps_per_call,
                block_len=tr_block, chunk_len=tr_chunk,
                num_blocks=tr_blocks, compute_dtype=compute_dtype,
                registry=obs_metrics.MetricsRegistry(),
                fault_injector=inj, flight_recorder=rec))
            injs.append(inj)
            recs.append(rec)
        rreg = obs_metrics.MetricsRegistry()
        rrec = FlightRecorder()
        mon = SLOBurnRateMonitor(slo_target=0.9, window_steps=8)
        ts = TimeSeriesRecorder(rreg, capacity=64)
        rt = Router(engs, failover=True, registry=rreg,
                    flight_recorder=rrec, monitor=mon, timeseries=ts)
        hs = [rt.submit(p, max_new_tokens=fo_new, arrival_time=0.0)
              for p in fo_prompts]
        rt.step(now=0.0)
        for _ in range(2):
            rt.step(now=0.0)
        vi = hs[0].engine
        injs[vi].force_swap(hs[0].request_id)
        injs[vi].fail_allocs(None)
        rt.step(now=0.0)
        vblocks = (hs[0]._req.swap.n_blocks
                   if hs[0].state == "swapped" else 0)
        injs[vi].kill_at_step(engs[vi]._step_idx + 1)
        steps = 0
        step_walls = []
        while any(h.state not in ("finished", "failed", "timeout",
                                  "shed", "cancelled") for h in hs):
            s0 = time.perf_counter()
            rt.step(now=0.0)
            step_walls.append(time.perf_counter() - s0)
            steps += 1
            if steps > 400:
                break
        outs = [np.asarray(h.output) for h in hs]
        return (rt, recs, rrec, mon, ts, outs, vi, vblocks,
                hs[0].router_id, step_walls)

    (obs_rt, obs_recs, obs_rrec, obs_mon, obs_ts, obs_outs,
     obs_vi, obs_vblocks, obs_victim_rid, obs_walls) = _one_obs_trace()
    obs_st = obs_rt.stitched_record()
    obs_ring = (len(obs_rrec.events())
                + sum(len(r.events()) for r in obs_recs))
    obs_story = obs_st.explain(obs_victim_rid)
    obs_hop = (f"migrated {obs_vblocks} blocks to engine "
               f"{1 - obs_vi} at exact bytes")
    obs_alerts = obs_mon.alerts()

    # disabled-mode micro-bench (the PR-2 shape): one busy router
    # step's worth of recorder emits plus a time-series sample on
    # DISABLED instances vs the measured instrumented step wall
    rec_d = FlightRecorder(enabled=False)
    ts_d = TimeSeriesRecorder(obs_metrics.MetricsRegistry(),
                              capacity=8, enabled=False)

    def _touches():
        rec_d.emit("submit", 1, 0, seq_len=6, max_new=8, priority=0,
                   queue_depth=1)
        rec_d.emit("route", 1, 0, engine=0, queue_depth=1)
        rec_d.emit("admit", 1, 1, slot=0, matched_blocks=0)
        rec_d.emit("prefill_chunk", 1, 1, start=0, tokens=6)
        rec_d.emit("decode_block", 1, 2, steps=1)
        rec_d.emit("decode_block", 2, 2, steps=1)
        rec_d.emit("migrate", 1, 3, engine=1, src=0, blocks=3)
        rec_d.emit("finish", 1, 9, tokens=8)
        ts_d.sample(0)

    n_micro = 3000
    t0 = time.perf_counter()
    for _ in range(n_micro):
        _touches()
    t_disabled = (time.perf_counter() - t0) / n_micro
    t_step = float(np.median(obs_walls)) if obs_walls else 1.0

    fleet_obs_ab = {
        "replicas": 2, "n_requests": len(fo_prompts),
        "stitched_events": len(obs_st),
        "ring_events": int(obs_ring),
        "stitched_dropped": int(obs_st.dropped_total),
        "victim_replica": int(obs_vi),
        "victim_parcel_blocks": int(obs_vblocks),
        "alerts": [{"kind": a["kind"], "step": a["step"]}
                   for a in obs_alerts],
        "timeseries_samples": len(obs_ts),
        "timeseries_dropped": int(obs_ts.dropped),
        # walls: report-only, never gated on magnitude
        "step_ms": round(1e3 * t_step, 3),
        "disabled_emit_us": round(1e6 * t_disabled, 3),
        "overhead_pct": round(100.0 * t_disabled / max(t_step, 1e-9),
                              4),
        # deterministic gates: every ring event survives stitching
        # (nothing dropped, nothing duplicated), explain() narrates
        # exactly one migrate hop at the victim's exact parcel size,
        # the kill raises exactly one replica_unhealthy alert, the
        # instrumented trace is token-exact vs the uninstrumented ON
        # arm, and the disabled plane costs <2% of a step
        "gate_stitch_count_exact": bool(
            len(obs_st) == obs_ring and obs_st.dropped_total == 0),
        "gate_migrate_hop_rendered": bool(
            obs_hop in obs_story
            and obs_story.count("migrated ") == 1
            and obs_vblocks > 0),
        "gate_alert_once_on_kill": bool(
            len(obs_alerts) == 1
            and obs_alerts[0]["kind"] == "replica_unhealthy"
            and obs_alerts[0]["engine"] == obs_vi),
        "gate_obs_token_exact": bool(all(
            np.array_equal(a, b)
            for a, b in zip(fo_on_outs, obs_outs))),
        "gate_disabled_under_2pct": bool(t_disabled < 0.02 * t_step),
    }

    # -- multichip arm (``multichip`` sub-object, PR 18): the mesh-
    # sharded serving dryrun, MULTICHIP_r*-shaped — re-exec this file
    # as a child with xla_force_host_platform_device_count=8 (the
    # parent's device topology is whatever it is; the dryrun always
    # gets 8 virtual host devices) and gate ONLY on the deterministic
    # counters the child ships back: tensor-parallel decode is
    # token-exact and dispatch-count-identical to single-chip, the
    # sharded route overlay really advanced, data-parallel shard-group
    # replicas behind the Router stay token-exact across the topology
    # change, and the fleet surfaces the expected shard-group labels.
    # tokens/s scaling and per-replica occupancy are REPORT-ONLY
    # walls (this box is jitter-bound per ROADMAP).
    import os as _os
    import subprocess
    import sys as _sys
    _env = dict(_os.environ)
    _env["XLA_FLAGS"] = (_env.get("XLA_FLAGS", "")
                         + " --xla_force_host_platform_device_count=8"
                         ).strip()
    _env["JAX_PLATFORMS"] = "cpu"
    try:
        _proc = subprocess.run(
            [_sys.executable, _os.path.abspath(__file__),
             "--serving-multichip-child"],
            capture_output=True, text=True, timeout=900, env=_env)
        if _proc.returncode != 0:
            raise RuntimeError(
                f"child rc={_proc.returncode}: {_proc.stderr[-300:]}")
        mc = json.loads(_proc.stdout.strip().splitlines()[-1])
        multichip = {
            "devices": mc["devices"],
            "tp": mc["tp"],
            "dp": mc["dp"],
            "gate_tp_token_exact": bool(mc["tp"]["token_exact"]),
            "gate_tp_dispatch_parity": bool(
                mc["tp"]["dispatch_parity"]),
            "gate_sharded_route": bool(
                mc["tp"]["sharded_ok_delta"] > 0),
            "gate_dp_token_exact": bool(mc["dp"]["token_exact"]),
            "gate_shard_groups": bool(
                mc["dp"]["shard_groups"] == ["tp2@d0", "tp2@d2"]),
            # report-only: wall-derived throughput scaling
            "dp_scaling": mc["dp"]["scaling"],
        }
    except Exception as e:                      # keep the bench JSON whole
        multichip = {"error": str(e)[:300]}

    # -- multiproc arm (``multiproc`` sub-object, PR 19): REAL
    # EngineProcess children behind SocketTransport proxies, the
    # failover-arm kill trace with an actual process death — the
    # victim child arms FaultInjector.exit_at_step and os._exit()s
    # mid-trace, which the parent only sees as a dead socket
    # (TransportDeadError -> the PR-15 failover paths).  Gated ONLY on
    # deterministic counters: socket outputs token-exact vs an
    # in-process reference built from the SAME factory, migration
    # moved exactly the victim's staged parcel, and the per-replica
    # frame counts (by kind) are equal across two reruns of the whole
    # trace — the frame-sequence determinism contract.  Walls (spawn,
    # rpc) are REPORT-ONLY: sockets are slow/bench-only by design.
    try:
        from paddle_tpu.inference.procserve import (EngineProcess,
                                                    TCPStoreLite,
                                                    tiny_llama_engine)
        from paddle_tpu.inference.transport import (RemoteReplica,
                                                    SocketTransport)

        mp_rng = np.random.default_rng(29)
        mp_prompts = [mp_rng.integers(1, 128, (int(n),)).astype(np.int32)
                      for n in mp_rng.integers(6, 12, 4)]
        mp_new = 8
        _FACTORY = "paddle_tpu.inference.procserve:tiny_llama_engine"
        # the victim (child 0) force-swaps its first request at step 6
        # (parking it via always-failing allocs so the parcel stays
        # staged on the client), then dies for real two steps later
        _FAULT = {"force_swap_rid": 0, "force_swap_step": 6,
                  "park_allocs": True, "exit_at_step": 8}

        def _mp_reference():
            engs = [tiny_llama_engine() for _ in range(2)]
            rt = Router(engs, registry=obs_metrics.MetricsRegistry())
            hs = [rt.submit(p, max_new_tokens=mp_new,
                            arrival_time=0.0) for p in mp_prompts]
            for _ in range(400):
                rt.step(now=0.0)
                if all(h.state in ("finished", "failed")
                       for h in hs):
                    break
            return [np.asarray(h.output) for h in hs]

        def _mp_socket_trace():
            store_addr, closer = TCPStoreLite.serve()
            procs, reps = [], []
            try:
                for i in range(2):
                    kw = {"fault_spec": _FAULT} if i == 0 else {}
                    procs.append(EngineProcess(
                        f"mp{i}", _FACTORY, kw, store_addr))
                t0 = time.perf_counter()
                reps = [RemoteReplica(SocketTransport(
                            p, registry=obs_metrics.MetricsRegistry(),
                            rpc_timeout_s=300.0)) for p in procs]
                t_handshake = time.perf_counter() - t0
                rt = Router(reps,
                            registry=obs_metrics.MetricsRegistry())
                hs = [rt.submit(p, max_new_tokens=mp_new,
                                arrival_time=0.0)
                      for p in mp_prompts]
                vblocks = 0
                for _ in range(400):
                    rt.step(now=0.0)
                    for h in hs:
                        if h.state == "swapped" \
                                and h._req.swap is not None:
                            vblocks = h._req.swap.n_blocks
                    if all(h.state in ("finished", "failed")
                           for h in hs):
                        break
                wall = time.perf_counter() - t0
                rs = rt.stats()
                return {
                    "outs": [np.asarray(h.output) for h in hs],
                    "frames": [r.transport_stats()["frames"]
                               for r in reps],
                    "bytes_out": [r.transport_stats()["bytes_out"]
                                  for r in reps],
                    "replica_faults": rs["replica_faults"],
                    "failover_requests": rs["failover_requests"],
                    "migrated_blocks": rs["migrated_blocks"],
                    "migrated_bytes": rs["migrated_bytes"],
                    "victim_parcel_blocks": int(vblocks),
                    "victim_gen": procs[0].gen,
                    "completion": sum(h.state == "finished"
                                      for h in hs) / len(hs),
                    "handshake_ms": round(1e3 * t_handshake, 1),
                    "wall_ms": round(1e3 * wall, 1),
                }
            finally:
                for r in reps:
                    try:
                        r._t.close()
                    except Exception:
                        pass
                for p in procs:
                    p.kill()
                closer()

        mp_ref_outs = _mp_reference()
        mp_a = _mp_socket_trace()
        mp_b = _mp_socket_trace()
        multiproc = {
            "replicas": 2, "n_requests": len(mp_prompts),
            "max_new": mp_new,
            "replica_faults": mp_a["replica_faults"],
            "failover_requests": mp_a["failover_requests"],
            "migrated_blocks": mp_a["migrated_blocks"],
            "migrated_bytes": mp_a["migrated_bytes"],
            "victim_parcel_blocks": mp_a["victim_parcel_blocks"],
            "frames_by_kind": mp_a["frames"],
            # a real process died (the supervisor respawned it as
            # generation 1) and every request still completed
            # token-for-token equal to the no-fault in-process
            # reference; migration moved exactly the victim's parcel
            "gate_token_exact": bool(
                mp_a["completion"] == 1.0
                and all(np.array_equal(a, b) for a, b in
                        zip(mp_ref_outs, mp_a["outs"]))),
            "gate_real_process_death": bool(
                mp_a["victim_gen"] >= 1
                and mp_a["replica_faults"] >= 1),
            "gate_migrated_blocks_exact": bool(
                mp_a["victim_parcel_blocks"] > 0
                and mp_a["migrated_blocks"]
                == mp_a["victim_parcel_blocks"]),
            # frame counts per kind equal across two full reruns —
            # deterministic sequences, not byte totals (payload floats
            # may format differently), though bytes are reported
            "gate_frames_deterministic": bool(
                mp_a["frames"] == mp_b["frames"]),
            # report-only walls
            "handshake_ms": mp_a["handshake_ms"],
            "wall_ms": [mp_a["wall_ms"], mp_b["wall_ms"]],
            "bytes_out": mp_a["bytes_out"],
        }
    except Exception as e:                      # keep the bench JSON whole
        multiproc = {"error": str(e)[:300]}

    # -- disaggregated prefill/decode arm (``disagg`` sub-object,
    # PR 20): a mixed long-prefill + interactive trace through TWO
    # fleets — disagg (1 prefill + 1 decode replica, chunk-final
    # handoff through the router stage) vs monolithic (2 "both"
    # replicas).  Gated ONLY on deterministic counters: per-request
    # token exactness across arms, handoff count == chunk-final count
    # on the prefill replica, the migrated parcel blocks exact
    # (router handoff events sum to the engine's handoff_blocks),
    # ZERO prefill chunks dispatched on the decode replica, and
    # counter equality across two full reruns.  The TTFT/TPOT split —
    # disaggregation's whole point is isolating decode TPOT from
    # prefill bursts — is wall-shaped and therefore REPORT-ONLY --
    try:
        dg_rng = np.random.default_rng(31)
        dg_prompts = []
        for i in range(6):
            # even = long prefill burst (multi-chunk), odd = short
            # interactive prompt riding alongside
            lo, hi = ((2 * tr_chunk - 4, 2 * tr_chunk) if i % 2 == 0
                      else (4, tr_user + 4))
            n = int(dg_rng.integers(lo, hi))
            dg_prompts.append(dg_rng.integers(
                0, cfg.vocab_size, (n,)).astype(np.int32))
        dg_new = 2 * tr_new

        def _one_disagg_trace(roles):
            recs = [FlightRecorder() for _ in roles]
            rrec = FlightRecorder()
            engs = [ServingEngine(
                model, num_slots=2, prompt_len=tr_prompt,
                max_cache_len=tr_cache, steps_per_call=steps_per_call,
                block_len=tr_block, chunk_len=tr_chunk,
                num_blocks=tr_blocks, compute_dtype=compute_dtype,
                registry=obs_metrics.MetricsRegistry(),
                flight_recorder=rec, role=role)
                for role, rec in zip(roles, recs)]
            rt = Router(engs, registry=obs_metrics.MetricsRegistry(),
                        flight_recorder=rrec)
            t0 = time.perf_counter()
            hs = [rt.submit(p, max_new_tokens=dg_new,
                            arrival_time=0.0, stream=False)
                  for p in dg_prompts]
            done = ("finished", "failed", "timeout", "shed",
                    "cancelled")
            first_step, finish_step = {}, {}
            steps = 0
            while any(h.state not in done for h in hs):
                rt.step(now=0.0)
                steps += 1
                for j, h in enumerate(hs):
                    if j not in first_step and len(h.tokens) > 0:
                        first_step[j] = steps
                    if j not in finish_step and h.state in done:
                        finish_step[j] = steps
                if steps > 400:
                    break
            wall = time.perf_counter() - t0
            outs = [np.asarray(h.output) for h in hs]
            stats = [e.stats() for e in engs]
            # the TTFT/TPOT split is the whole point of disaggre-
            # gation, but this trace runs on a constant step clock so
            # the gates stay deterministic — report the split in
            # router STEPS (step-indexed, rerun-stable), not wall ms
            ttfts, tpots = [], []
            for j, h in enumerate(hs):
                if j not in first_step:
                    continue
                ttfts.append(first_step[j])
                if j in finish_step and len(h.tokens) > 1:
                    tpots.append((finish_step[j] - first_step[j])
                                 / (len(h.tokens) - 1))
            counters = {
                "handoffs": [s["handoffs"] for s in stats],
                "handoff_blocks": [s["handoff_blocks"]
                                   for s in stats],
                "handoff_bytes": [s["handoff_bytes"] for s in stats],
                "prefills": [s["prefills"] for s in stats],
                "prefill_chunks": [
                    sum(e.kind == "prefill_chunk"
                        for e in rec.events()) for rec in recs],
                "decode_blocks": [
                    sum(e.kind == "decode_block"
                        for e in rec.events()) for rec in recs],
                "router_handoff_blocks": sum(
                    int(e.attrs.get("blocks", 0))
                    for e in rrec.events() if e.kind == "handoff"),
            }
            return {
                "roles": [s["role"] for s in stats],
                "counters": counters,
                "mean_ttft_steps": round(
                    float(np.mean(ttfts)), 2) if ttfts else None,
                "mean_tpot_steps": round(
                    float(np.mean(tpots)), 2) if tpots else None,
                "wall_ms": round(1e3 * wall, 1),
            }, outs

        dg_mono, dg_mono_outs = _one_disagg_trace(["both", "both"])
        dg_a, dg_a_outs = _one_disagg_trace(["prefill", "decode"])
        dg_b, dg_b_outs = _one_disagg_trace(["prefill", "decode"])
        ca, cb = dg_a["counters"], dg_b["counters"]
        # chunk-final count on the prefill replica: every request
        # that decoded past tok0 must have handed off exactly once
        # (tok0-terminal requests finish locally, never migrate)
        dg_expect_handoffs = sum(len(o) > 1 for o in dg_a_outs)
        disagg = {
            "replicas": 2, "n_requests": len(dg_prompts),
            "max_new": dg_new,
            "monolithic": dg_mono,
            "disagg": dg_a,
            "gate_token_exact": bool(all(
                np.array_equal(a, b)
                for a, b in zip(dg_mono_outs, dg_a_outs))),
            "gate_handoffs_exact": bool(
                ca["handoffs"][0] == dg_expect_handoffs
                and dg_expect_handoffs > 0
                and ca["handoffs"][1] == 0),
            "gate_parcel_blocks_exact": bool(
                ca["router_handoff_blocks"]
                == ca["handoff_blocks"][0] > 0),
            "gate_no_prefill_on_decode": bool(
                ca["prefill_chunks"][1] == 0
                and ca["prefills"][1] == 0
                and ca["prefill_chunks"][0] > 0),
            "gate_deterministic": bool(ca == cb),
        }
    except Exception as e:                      # keep the bench JSON whole
        disagg = {"error": str(e)[:300]}

    return {
        "tokens_per_s": cont["tokens_per_s"],
        "p50_latency_ms": cont["p50_latency_ms"],
        "p99_latency_ms": cont["p99_latency_ms"],
        "mean_slot_occupancy": cont["mean_slot_occupancy"],
        "static_tokens_per_s": stat["tokens_per_s"],
        "static_p50_latency_ms": stat["p50_latency_ms"],
        "static_p99_latency_ms": stat["p99_latency_ms"],
        "static_slot_occupancy": stat["mean_slot_occupancy"],
        "vs_static": round(
            cont["tokens_per_s"] / max(stat["tokens_per_s"], 1e-9), 3),
        "prefix": {
            "shared_fraction": 0.7, "shared_len": pf_shared,
            "block_len": pf_block, "chunk_len": pf_chunk,
            "tokens_per_s": pfx_on["tokens_per_s"],
            "no_cache_tokens_per_s": pfx_off["tokens_per_s"],
            "vs_no_cache": round(
                pfx_on["tokens_per_s"]
                / max(pfx_off["tokens_per_s"], 1e-9), 3),
            "mean_ttft_ms": pfx_on["mean_ttft_ms"],
            "no_cache_mean_ttft_ms": pfx_off["mean_ttft_ms"],
            "prefix_hit_rate": pfx_on["prefix_hit_rate"],
            "prefill_chunks": pfx_on["prefill_chunks"],
            "no_cache_prefill_chunks": pfx_off["prefill_chunks"],
            "peak_blocks_in_use": pfx_on["peak_blocks_in_use"],
            "no_cache_peak_blocks_in_use":
                pfx_off["peak_blocks_in_use"],
        },
        "prefix_tiered": {
            "block_len": tr_block, "hbm_blocks": tr_blocks,
            "system_len": tr_sys, "turns": tr_turns,
            "conversations": tr_convs,
            "tiered": tier_r,
            "digest": tier_d,
            "no_cache": tier_n,
            "hit_tokens_vs_digest": round(
                tier_r["hit_tokens"] / max(tier_d["hit_tokens"], 1), 3),
            "ttft_vs_digest": round(
                tier_r["mean_ttft_ms"]
                / max(tier_d["mean_ttft_ms"], 1e-9), 3),
        },
        "kv_int8": kv_int8,
        "weight_quant": weight_quant,
        "overload": overload,
        "async": async_ab,
        "async_depth": depth_ab,
        "lora": lora,
        "router": router_ab,
        "failover": failover_ab,
        "fleet_obs": fleet_obs_ab,
        "multichip": multichip,
        "multiproc": multiproc,
        "disagg": disagg,
        "spec": {
            "k": sp_k, "max_new": sp_new, "n_requests": sp_n,
            "tokens_per_s": spec_on["tokens_per_s"],
            "no_spec_tokens_per_s": spec_off["tokens_per_s"],
            "vs_no_spec": round(
                spec_on["tokens_per_s"]
                / max(spec_off["tokens_per_s"], 1e-9), 3),
            "mean_accepted_len": spec_on["mean_accepted_len"],
            "acceptance_rate": spec_on["acceptance_rate"],
            "drafts_per_token": spec_on["drafts_per_token"],
            "draft_hit_rate": spec_on["draft_hit_rate"],
            "verify_steps": spec_on["verify_steps"],
            "accepted_length_le": spec_on["accepted_length_le"],
            "accepted_length_counts":
                spec_on["accepted_length_counts"],
            # goodput ledger: deterministic token counts (conservation
            # gated exactly); the spec arm's wasted{spec_reject} is
            # the price of drafting priced in positions, the no-spec
            # run's goodput fraction is the same trace's ceiling.
            # mean_tpot_ms is wall — reported, never gated
            "goodput": spec_on["goodput"],
            "no_spec_goodput": spec_off["goodput"]["goodput"],
            "mean_tpot_ms": spec_on["mean_tpot_ms"],
            "no_spec_mean_tpot_ms": spec_off["mean_tpot_ms"],
        },
        "sampling": {
            "temperature": sa_temp, "top_k": sa_topk,
            "greedy_tokens_per_s": spec_off["tokens_per_s"],
            "sampled_tokens_per_s": samp_plain["tokens_per_s"],
            "spec_sampled_tokens_per_s": samp_spec["tokens_per_s"],
            "sampled_vs_greedy": round(
                samp_plain["tokens_per_s"]
                / max(spec_off["tokens_per_s"], 1e-9), 3),
            "spec_sampled_vs_sampled": round(
                samp_spec["tokens_per_s"]
                / max(samp_plain["tokens_per_s"], 1e-9), 3),
            "sampled_tokens": samp_plain["sampled_tokens"],
            "resamples": samp_spec["resamples"],
            "mean_accepted_len": samp_spec["mean_accepted_len"],
            "greedy_spec_mean_accepted_len":
                spec_on["mean_accepted_len"],
            "accepted_len_delta": round(
                samp_spec["mean_accepted_len"]
                - spec_on["mean_accepted_len"], 3),
            "acceptance_rate": samp_spec["acceptance_rate"],
        },
        "config": {"num_slots": num_slots, "prompt": prompt,
                   "cache_len": cache_len, "n_requests": n_requests,
                   "steps_per_call": steps_per_call,
                   "max_new_range": [int(new_lo), int(new_hi)],
                   "mean_arrival_gap_s": mean_gap,
                   "useful_tokens": int(news.sum()),
                   "dtype": compute_dtype},
    }


def _serving_multichip_child():
    """The ``multichip`` arm's dryrun body (see ``_bench_serving``):
    runs in a CHILD process whose XLA_FLAGS force 8 virtual host
    devices, so the mesh-sharded serving path executes a real 8-device
    SPMD program regardless of the parent's platform.  Prints ONE JSON
    line.  Three phases:

    - tensor-parallel A/B: one combined trace (chunked prefill +
      spec-decode verify + greedy decode) through a single-chip engine
      and a ``mesh=tp2`` engine — token streams, dispatch counts and
      the ``sharded_ok`` route-counter delta ship back as gate inputs;
    - data-parallel scaling: the same wider trace through a 1-replica
      and a 2-replica Router (each replica a tp2 shard group on its
      own device pair) — outputs must stay token-exact across the
      routing change (greedy rows; the host plan is topology-blind),
      walls/occupancy ship back report-only;
    - fleet identity: the 2-replica ``fleet_snapshot()`` shard-group
      labels."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import models
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.inference.router import Router
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.observability import metrics as obs_metrics

    paddle.seed(18)
    devs = jax.devices()
    cfg = models.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=64)
    net = models.LlamaForCausalLM(cfg)
    net.eval()
    rng = np.random.default_rng(18)

    def mk(mesh=None):
        return ServingEngine(
            net, num_slots=2, prompt_len=8, max_cache_len=32,
            steps_per_call=2, block_len=4, num_blocks=24, chunk_len=4,
            compute_dtype="float32",
            registry=obs_metrics.MetricsRegistry(), mesh=mesh)

    route = obs_metrics.get_registry().counter(
        "pallas.decode_attention.route", labels=("decision", "reason"))

    def shard_hits():
        return (route.value(decision="pallas", reason="sharded_ok")
                + route.value(decision="xla", reason="sharded_ok"))

    prompts = [rng.integers(0, cfg.vocab_size,
                            (int(n),)).astype(np.int32)
               for n in rng.integers(4, 9, 6)]
    # the spec-decode row repeats a 3-gram so the prompt-lookup
    # drafter has a chance to propose; its longer budget leaves
    # k_eff room if the greedy stream cycles
    pat = rng.integers(0, cfg.vocab_size, (3,)).astype(np.int32)
    prompts[2] = np.concatenate([pat, pat, pat[:1]])
    news = [4, 5, 8, 6, 4, 5]

    def tp_trace(eng):
        t0 = time.perf_counter()
        hs = [eng.submit(p, max_new_tokens=m,
                         spec_decode=(2 if i == 2 else None),
                         arrival_time=0.0)
              for i, (p, m) in enumerate(zip(prompts, news))]
        eng.run()
        wall = time.perf_counter() - t0
        s = eng.stats()
        return [h.output.tolist() for h in hs], wall, {
            "block_dispatches": s["block_dispatches"],
            "prefill_chunks": s["prefill_chunks"],
            "verify_steps": s["spec_verify_steps"],
            "prefills": s["prefills"],
            "finished": s["finished"],
        }

    out1, wall1, c1 = tp_trace(mk())
    base_hits = shard_hits()
    out2, wall2, c2 = tp_trace(
        mk(mesh=build_mesh(mp=2, devices=devs[:2])))

    def dp_trace(n_replicas):
        engs = [mk(mesh=build_mesh(mp=2, devices=devs[2 * i:2 * i + 2]))
                for i in range(n_replicas)]
        rt = Router(engs, registry=obs_metrics.MetricsRegistry())
        t0 = time.perf_counter()
        hs = [rt.submit(p, max_new_tokens=m, arrival_time=0.0)
              for p, m in zip(prompts, news)]
        rt.run()
        wall = time.perf_counter() - t0
        toks = sum(len(h.output) for h in hs)
        return ([h.output.tolist() for h in hs], toks / max(wall, 1e-9),
                [e.stats()["mean_slot_occupancy"] for e in engs],
                rt.fleet_snapshot()["shard_groups"])

    dp1_out, dp1_tps, _occ1, _sg1 = dp_trace(1)
    dp2_out, dp2_tps, occ2, sg2 = dp_trace(2)

    print(json.dumps({
        "devices": len(devs),
        "tp": {
            "token_exact": out1 == out2,
            "dispatch_parity": c1 == c2,
            "sharded_ok_delta": shard_hits() - base_hits,
            "counts": c1,
            "single_wall_ms": round(1e3 * wall1, 1),
            "tp2_wall_ms": round(1e3 * wall2, 1),
        },
        "dp": {
            "replicas": 2,
            "token_exact": dp1_out == dp2_out and dp1_out == out1,
            "tokens_per_s": round(dp2_tps, 1),
            "one_replica_tokens_per_s": round(dp1_tps, 1),
            "scaling": round(dp2_tps / max(dp1_tps, 1e-9), 3),
            "per_replica_occupancy": [round(o, 3) for o in occ2],
            "shard_groups": sg2,
        },
    }))


if __name__ == "__main__":
    import sys as _sys
    if "--serving-multichip-child" in _sys.argv:
        _serving_multichip_child()
    else:
        main()
