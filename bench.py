"""Benchmark: Llama pretraining step at memory-pressured scale — reports MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
Runs the fully-compiled TrainStep (forward+loss+backward+AdamW) in bf16 with
per-layer rematerialization on whatever device jax exposes (the real TPU chip
under the driver; CPU otherwise, scaled-down shapes).

Model-FLOPs accounting (BASELINE.md north star is Llama-3-8B >=40% MFU):
  flops/token = 6 * N_matmul + 6 * L * seq * hidden
where N_matmul excludes the input embedding table (a gather, not a matmul;
the lm_head projection IS counted) and the attention term counts the causal
QK^T and AV matmuls for forward + backward (2 matmuls * 2 FLOP/MAC *
seq^2/2 causal * hidden * 3 passes = 6*seq^2*hidden per layer).

vs_baseline = mfu / 0.40 — >= 1.0 means the north-star gate is met.

The config ladder walks down from the largest setting until one fits in
HBM; the chosen config is reported in the JSON line.  A separate matmul
microbenchmark validates the nominal peak-FLOPs constant against silicon,
and the lowered StableHLO is scanned for tpu_custom_call to prove the
Pallas kernels (flash attention, rms norm, rope) are in the hot loop.
"""

import json
import time

import numpy as np


def _measure_matmul_peak(jnp, jax):
    """Time a large bf16 matmul chain to sanity-check the peak-FLOPs
    constant.  One jit call with the loop inside (the axon tunnel adds
    per-call latency) and a matrix big enough to be compute-bound
    (16384^2 bf16; smaller sizes are HBM-bound on v5e)."""
    n = 16384
    iters = 16
    x = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def chain(a):
        def body(_, acc):
            return jnp.matmul(acc, acc,
                              preferred_element_type=jnp.float32
                              ).astype(jnp.bfloat16)
        return jax.lax.fori_loop(0, iters, body, a)

    np.asarray(chain(x)[0, :8])  # compile + warmup
    t0 = time.perf_counter()
    out = chain(x)
    np.asarray(out[0, :8])  # host fetch drains the chain
    dt = time.perf_counter() - t0
    return iters * 2 * n ** 3 / dt


def main():
    import jax

    from paddle_tpu.models import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")

    if on_tpu:
        peak_flops = 197e12  # v5e nominal bf16 (v5p would be 459e12)
        dtype = "bfloat16"
        steps = 10
        # largest-fits ladder: ~1.1B params (h2048/L16/i8192) down to the
        # round-1 0.49B config; 16G HBM must hold bf16 params + fp32 m/v
        # (10 bytes/param) + remat activations
        ladder = [
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=16, num_attention_heads=32,
                 num_key_value_heads=8, batch=8, seq=2048),
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=16, num_attention_heads=32,
                 num_key_value_heads=8, batch=4, seq=2048),
            dict(hidden_size=2048, intermediate_size=8192,
                 num_hidden_layers=12, num_attention_heads=32,
                 num_key_value_heads=8, batch=4, seq=2048),
            dict(hidden_size=2048, intermediate_size=5632,
                 num_hidden_layers=8, num_attention_heads=16,
                 num_key_value_heads=8, batch=8, seq=1024),
        ]
    else:
        peak_flops = 1e11
        dtype = "float32"
        steps = 3
        ladder = [dict(hidden_size=256, intermediate_size=704,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, batch=2, seq=128,
                       vocab_size=1024)]

    last_err = None
    for lad in ladder:
        batch, seq = lad.pop("batch"), lad.pop("seq")
        cfg = LlamaConfig(vocab_size=lad.pop("vocab_size", 32000),
                          max_position_embeddings=seq,
                          recompute=on_tpu, **lad)
        try:
            result = _run(cfg, batch, seq, steps, dtype, peak_flops, on_tpu)
            break
        except Exception as e:  # OOM -> walk down the ladder
            if "RESOURCE_EXHAUSTED" in str(e) or "Out of memory" in str(e):
                # keep only the message: the traceback's _run frame pins
                # the failed config's params/opt state in HBM
                last_err = str(e)[:500]
                continue
            raise
    else:
        raise RuntimeError(f"no bench config fit in memory: {last_err}")

    print(json.dumps(result))


def _run(cfg, batch, seq, steps, dtype, peak_flops, on_tpu):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import LlamaForCausalLM, LlamaPretrainingCriterion

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    model.train()
    if dtype == "bfloat16":
        model.to(dtype="bfloat16")
    criterion = LlamaPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=(dtype == "bfloat16"))

    def loss_fn(net, tokens, labels):
        logits = net(tokens)
        return criterion(logits, labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    tokens = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # warmup / compile.  Sync via a host fetch of the loss: on the axon
    # PJRT tunnel block_until_ready() acks the enqueue, not completion —
    # only a device->host transfer truly drains the step chain.
    loss = step(tokens, labels)
    float(loss)

    # Pallas-kernel presence check: the lowered program must contain
    # tpu_custom_call (flash attention / rms norm / rope kernels)
    pallas_in_hlo = False
    try:
        lowered = step._compiled.lower(
            [p._value for p in step._params], step._state, step._gm_state,
            jax.random.PRNGKey(0), jnp.float32(1e-4),
            [b._value for b in step._buffers],
            tokens._value, labels._value)
        pallas_in_hlo = "tpu_custom_call" in lowered.as_text()
    except Exception:
        pass

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(tokens, labels)
    float(loss)  # true device sync (chained through every step's params)
    dt = time.perf_counter() - t0

    tokens_per_s = batch * seq * steps / dt

    n_params = sum(p.size for p in model.parameters())
    n_embed = model.llama.embed_tokens.weight.size
    n_matmul = n_params - n_embed  # lm_head stays (it is a matmul)
    flops_per_token = (6.0 * n_matmul +
                       6.0 * cfg.num_hidden_layers * seq * cfg.hidden_size)
    flops_per_s = flops_per_token * tokens_per_s
    mfu = flops_per_s / peak_flops

    measured_peak = None
    if on_tpu:
        try:
            measured_peak = _measure_matmul_peak(jnp, jax)
        except Exception:
            pass

    return {
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 3),
        "mfu": round(mfu, 4),
        "model_params": int(n_params),
        "config": {"hidden": cfg.hidden_size, "layers": cfg.num_hidden_layers,
                   "intermediate": cfg.intermediate_size, "batch": batch,
                   "seq": seq, "dtype": dtype},
        "flops_per_token": round(flops_per_token / 1e9, 3),
        "peak_flops_nominal": peak_flops,
        "measured_matmul_flops": (round(measured_peak / 1e12, 1) * 1e12
                                  if measured_peak else None),
        "pallas_in_hlo": pallas_in_hlo,
    }


if __name__ == "__main__":
    main()
