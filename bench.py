"""Benchmark: Llama decoder pretraining step throughput (tokens/sec/chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Runs the fully-compiled TrainStep (forward+loss+backward+AdamW, bf16 compute
via AMP-style param dtype) on whatever device jax exposes (the real TPU chip
under the driver; CPU otherwise, scaled-down shapes).

vs_baseline: the reference publishes no in-tree numbers (BASELINE.md);
we report the ratio of achieved model FLOPs/s to a 10% MFU floor on the
chip's nominal bf16 peak — >1.0 means we beat that conservative floor.
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    dev = jax.devices()[0]
    on_tpu = dev.platform in ("tpu", "axon")

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048)
        batch, seq, steps = 8, 1024, 10
        peak_flops = 197e12  # v5p nominal bf16; v5e ~394/2... conservative
        if "v5 lite" in str(dev).lower() or "v5e" in str(dev).lower():
            peak_flops = 197e12
        dtype = "bfloat16"
    else:
        cfg = LlamaConfig(vocab_size=1024, hidden_size=256,
                          intermediate_size=704, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2)
        batch, seq, steps = 2, 128, 3
        peak_flops = 1e11
        dtype = "float32"

    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    if dtype == "bfloat16":
        model.to(dtype="bfloat16")
    criterion = LlamaPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 multi_precision=(dtype == "bfloat16"))

    def loss_fn(net, tokens, labels):
        logits = net(tokens)
        return criterion(logits, labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.default_rng(0)
    tokens = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32))

    # warmup / compile.  Sync via a host fetch of the loss: on the axon
    # PJRT tunnel block_until_ready() acks the enqueue, not completion —
    # only a device->host transfer truly drains the step chain.
    loss = step(tokens, labels)
    float(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(tokens, labels)
    float(loss)  # true device sync (chained through every step's params)
    dt = time.perf_counter() - t0

    tokens_per_s = batch * seq * steps / dt

    # model FLOPs: 6 * n_params * tokens (dense decoder approximation)
    n_params = sum(p.size for p in model.parameters())
    flops_per_s = 6.0 * n_params * tokens_per_s
    mfu_floor_ratio = flops_per_s / (0.10 * peak_flops)

    print(json.dumps({
        "metric": "llama_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_s, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu_floor_ratio, 3),
    }))


if __name__ == "__main__":
    main()
