"""CTR-style parameter-server training: PS-resident sparse embeddings
with adagrad accessors, spill-to-disk budgets, and the HBM hot cache.

One process demo (server + trainer in-process):
    python examples/ps_ctr.py --steps 50

For the multi-process launch form see tests/test_parameter_server.py
(fleet.init_server/init_worker over `python -m paddle_tpu.distributed.launch
--server_num N`).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import tempfile

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=100000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--hot-rows", type=int, default=1024)
    ap.add_argument("--max-mem-rows", type=int, default=4096)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.ps import PSClient, PSServer
    from paddle_tpu.incubate.distributed import HBMEmbedding

    server = PSServer(0)
    client = PSClient("127.0.0.1", server.port)
    spill = os.path.join(tempfile.mkdtemp(), "ctr_table.spill")
    # cold store: adagrad accessor + spill budget (ssd_sparse_table role)
    paddle.seed(0)
    emb = HBMEmbedding(args.vocab, args.dim, hot_rows=args.hot_rows,
                       ps_client=client, table_id=1, sync_interval=10,
                       learning_rate=0.05)
    client.create_sparse_table(2, args.dim, init_scale=0.01,
                               sgd_rule="adagrad",
                               max_mem_rows=args.max_mem_rows,
                               spill_path=spill)
    head = nn.Sequential(nn.Linear(args.dim, 16), nn.ReLU(),
                         nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(
        learning_rate=0.01,
        parameters=list(emb.parameters()) + list(head.parameters()))

    rng = np.random.default_rng(0)
    # zipf-ish id distribution: a hot head + a long tail (what the HBM
    # cache and the spill budget are for)
    hot_ids = rng.integers(0, 200, size=10_000)
    tail_ids = rng.integers(200, args.vocab, size=10_000)

    losses = []
    for step in range(args.steps):
        take_hot = rng.random(args.batch) < 0.8
        ids = np.where(take_hot,
                       rng.choice(hot_ids, args.batch),
                       rng.choice(tail_ids, args.batch)).astype(np.int64)
        y = (ids % 2 == 0).astype(np.float32)[:, None]  # learnable signal
        x = paddle.to_tensor(ids)
        target = paddle.to_tensor(y)
        out = head(emb(x))
        loss = ((out - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
        if (step + 1) % 10 == 0:
            print(f"step {step + 1}: loss {losses[-1]:.4f} | "
                  f"hot rows resident: {len(emb.resident_ids)} | "
                  f"server keys: {client.sparse_table_size(1)}")

    assert losses[-1] < losses[0], "no learning signal?"
    print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")
    client.close()
    server.stop()


if __name__ == "__main__":
    main()
