"""Llama pretraining — the flagship recipe (BASELINE.md configs 3/5).

Single chip:
    python examples/llama_pretrain.py --layers 4 --steps 20

Multi-device mesh (TP x DP x ZeRO; CPU simulation works too):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/llama_pretrain.py --dp 2 --mp 2 --sharding 2 \
        --layers 2 --hidden 64 --steps 5

The full training step (forward + loss + backward + AdamW + ZeRO layouts)
compiles into ONE XLA program; GSPMD shards it over the mesh from the
layer annotations.  Gradient merge: --accumulate N.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--intermediate", type=int, default=8192)
    ap.add_argument("--layers", type=int, default=16)
    ap.add_argument("--heads", type=int, default=32)
    ap.add_argument("--kv-heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--sharding", type=int, default=1)
    ap.add_argument("--zero", choices=["os", "os_g", "p_g_os"], default=None)
    ap.add_argument("--accumulate", type=int, default=1)
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--recompute", action="store_true", default=True)
    ap.add_argument("--save", type=str, default=None)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM,
                                   LlamaPretrainingCriterion)

    parallel = args.dp * args.mp * args.sharding > 1
    if parallel:
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet.fleet_base import (
            DistributedStrategy)
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": args.dp, "mp_degree": args.mp, "pp_degree": 1,
            "sharding_degree": args.sharding, "sep_degree": 1,
        }
        fleet.init(is_collective=True, strategy=strategy)

    paddle.seed(0)
    cfg = LlamaConfig(
        vocab_size=args.vocab, hidden_size=args.hidden,
        intermediate_size=args.intermediate, num_hidden_layers=args.layers,
        num_attention_heads=args.heads, num_key_value_heads=args.kv_heads,
        max_position_embeddings=args.seq, recompute=args.recompute,
        tensor_parallel=args.mp > 1)
    model = LlamaForCausalLM(cfg)
    model.train()
    if args.bf16:
        model.to(dtype="bfloat16")
    criterion = LlamaPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(
        learning_rate=args.lr, parameters=model.parameters(),
        grad_clip=nn.ClipGradByGlobalNorm(1.0),
        multi_precision=args.bf16)
    if args.zero:
        import paddle_tpu.distributed as dist
        model, opt, _ = dist.group_sharded_parallel(model, opt, args.zero)

    def loss_fn(net, tokens, labels):
        return criterion(net(tokens), labels)

    step = TrainStep(model, loss_fn, opt,
                     accumulate_steps=args.accumulate)

    n_params = sum(p.size for p in model.parameters())
    print(f"model: {n_params / 1e9:.2f}B params | "
          f"mesh dp={args.dp} mp={args.mp} sharding={args.sharding} | "
          f"b{args.batch} s{args.seq} accumulate={args.accumulate}")

    rng = np.random.default_rng(0)
    tokens = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size,
                     (args.batch, args.seq)).astype(np.int32))
    labels = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size,
                     (args.batch, args.seq)).astype(np.int32))

    loss = step(tokens, labels)
    print(f"step 0 (compile): loss {float(loss):.4f}")
    t0 = time.perf_counter()
    for i in range(1, args.steps + 1):
        loss = step(tokens, labels)
        if i % 10 == 0 or i == args.steps:
            dt = time.perf_counter() - t0
            tps = args.batch * args.seq * i / dt
            print(f"step {i}: loss {float(loss):.4f} "
                  f"({tps:,.0f} tokens/s)")
    if args.save:
        paddle.save(model.state_dict(), args.save + ".pdparams")
        paddle.save(opt.state_dict(), args.save + ".pdopt")
        print(f"saved checkpoint to {args.save}.pdparams/.pdopt")


if __name__ == "__main__":
    main()
