"""Cached-KV LLM serving end-to-end (the fused_multi_transformer role).

Flow: build a Llama -> greedy generate (ONE compiled dispatch for
prefill + the whole decode scan) -> LLMPredictor session (block decode,
K tokens per dispatch) -> save/load the serving artifact -> weight-only
int8.  Runs in seconds on CPU with the tiny config; on a TPU chip the
same code serves the 1.1B bench config at the BASELINE.md decode
numbers (int8 ~1.6-2.4x bf16 at batch 1).

Run: python examples/llama_serve.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import models
from paddle_tpu.inference import LLMPredictor
from paddle_tpu.quantization import weight_only_quantize


def main():
    paddle.seed(0)
    cfg = models.tiny_llama_config()
    net = models.LlamaForCausalLM(cfg)
    net.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, (2, 8))

    # 1) model.generate: one compiled call, static KV cache
    toks = net.generate(paddle.to_tensor(prompt), max_new_tokens=12,
                        eos_token_id=None)
    print("generate:", np.asarray(toks._value)[0])

    # 2) serving session: prefill once, then decode incrementally in
    #    blocks (each block = one dispatch)
    pred = LLMPredictor(net, batch=2, prompt_len=8, max_cache_len=32,
                        steps_per_call=4)
    first = pred.start(prompt)
    more = pred.decode(11)
    session = np.concatenate([first[:, None], more], axis=1)
    print("session :", session[0])

    # 3) the artifact round-trip (StableHLO prefill + decode-block
    #    programs + weights; loads without the model class)
    with tempfile.TemporaryDirectory() as td:
        pred.save(td + "/llama_serve")
        loaded = LLMPredictor.load(td + "/llama_serve")
        again = loaded.generate(prompt, max_new_tokens=12)
    assert np.array_equal(again, session), "artifact must reproduce"
    print("artifact:", again[0], "(deterministic)")

    # 4) weight-only int8: halve the weight stream (decode is
    #    weight-streaming bound — BASELINE.md roofline)
    qnet = weight_only_quantize(net, inplace=False,
                                skip=lambda name, l: name == "lm_head")
    qpred = LLMPredictor(qnet, batch=2, prompt_len=8, max_cache_len=32,
                         steps_per_call=4)
    print("int8    :", qpred.generate(prompt, max_new_tokens=12)[0])


if __name__ == "__main__":
    main()
