"""ResNet on CIFAR-10 with the hapi Model API (BASELINE.md config 1).

Synthetic data (hermetic):
    python examples/resnet_cifar.py --epochs 1

Real CIFAR archive:
    python examples/resnet_cifar.py --data-file /path/cifar-10-python.tar.gz
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-file", type=str, default=None)
    ap.add_argument("--arch", type=str, default="resnet18")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--amp", type=str, default=None,
                    choices=[None, "O1", "O2"])
    ap.add_argument("--num-workers", type=int, default=0)
    ap.add_argument("--export", type=str, default=None,
                    help="prefix to export the inference artifact")
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.static import InputSpec
    from paddle_tpu.vision import models as vmodels
    from paddle_tpu.vision.datasets import Cifar10

    paddle.seed(0)
    train = Cifar10(data_file=args.data_file, mode="train")
    test = Cifar10(data_file=args.data_file, mode="test")

    net = getattr(vmodels, args.arch)(num_classes=10)
    model = paddle.Model(net, inputs=[InputSpec((1, 3, 32, 32), "float32")])
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=args.lr,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=paddle.metric.Accuracy(),
        amp_configs=args.amp)
    model.fit(train, eval_data=test, epochs=args.epochs,
              batch_size=args.batch_size, num_workers=args.num_workers,
              verbose=2)
    print(model.evaluate(test, batch_size=args.batch_size, verbose=0))
    if args.export:
        model.save(args.export, training=False)
        print(f"inference artifact exported to {args.export}.ptpu_model")


if __name__ == "__main__":
    main()
