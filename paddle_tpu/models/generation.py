"""Autoregressive generation over a static KV cache — the TPU-native
decode-serving engine.

Capability analogue of the reference's fused decode stack:
``paddle/fluid/operators/fused/fused_multi_transformer_op.cu`` (cached-KV
transformer decode) layered over ``masked_multihead_attention`` (single
decode step; our tested functional lives in
``incubate/nn/functional/__init__.py``) and PaddleNLP's ``generate()``
loop.  TPU-first design decisions:

- The WHOLE generation (prefill + every decode step) is one compiled
  XLA call: ``lax.scan`` over the step body with a static step count.
  One dispatch per request instead of one per token — through the axon
  tunnel a per-token dispatch costs ~6-10 ms, which at serving batch 1
  would dominate the ~2-3 ms weight-streaming step itself.
- The KV cache is a static-shape ``[B, max_cache_len, H_kv*D]`` ring
  of slots per layer (all heads of a slot contiguous in lanes — tile-
  aligned at rest, one contiguous DMA per prefix chunk); new tokens
  land via batched row scatter and validity masking hides unwritten
  slots — the static-shape formulation of the reference's in-place
  growing cache (its mmha kernel writes at ``sequence_lengths`` the
  same way).  Decode attention streams ONLY the valid prefix
  (ops/pallas/decode_attention.py).
- Float params are cast to the serving compute dtype ONCE per call,
  outside the scan: XLA materializes an optimally-tiled bf16 copy that
  streams at the measured ~975 GB/s, vs ~340 GB/s for bf16-stored
  arrays (v5e layout trap, BASELINE.md) — and the scan body then reads
  the fast copy every step.
- Decode attention is GQA-aware grouped einsum with fp32 softmax; the
  per-step HBM cost is exactly one cache sweep, which together with one
  weight sweep is the decode roofline: tokens/s ~= HBM_BW /
  (param_bytes/B + kv_bytes_per_token).

Greedy and sampled decoding (temperature / top-k) with EOS tracking are
supported; the compiled program is cached per (shape, option) bucket.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


@dataclass(frozen=True)
class GenerationConfig:
    """Static (trace-time) generation options.

    Reference analogue: PaddleNLP ``GenerationConfig`` feeding the
    fused_multi_transformer serving path; every field here is a compile
    -time constant of the exported program.
    """
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0                   # 0 = full softmax
    top_p: float = 1.0               # nucleus sampling; 1.0 = off
    num_beams: int = 1               # >1 = beam search (greedy scoring)
    length_penalty: float = 0.0      # beam score /= len**alpha at selection
    eos_token_id: Optional[int] = None
    pad_token_id: int = 0
    compute_dtype: str = "bfloat16"  # serving precision; params cast once
    cache_dtype: Optional[str] = None  # default: compute_dtype


def init_kv_cache(num_layers, batch, max_cache_len, num_kv_heads, head_dim,
                  dtype):
    """Per-layer (k, v) static slot buffers.  Packed ``[B, S, H_kv*D]``
    (all heads of one slot contiguous in lanes) when the head geometry
    allows, else plain [B, S, H_kv, D]
    (ops/pallas/decode_attention.cache_shape).

    Round-5 layout: a trailing D=64 dim lane-pads every row at rest
    (TPU arrays tile to (sublane, 128)) — 2x HBM and half-rate
    streaming (~373 GB/s measured in-model).  The packed form is
    exactly tile-aligned, keeps the decode scatter a plain row scatter,
    and lets the flash-decode kernel stream ONLY the valid prefix in
    contiguous chunks.
    """
    from ..ops.pallas.decode_attention import cache_shape
    shape = cache_shape(batch, num_kv_heads, max_cache_len, head_dim)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]


def cache_scatter(cache, lens, new_kv):
    """Write one new [B, H_kv, D] entry at each sequence's slot
    (row ``lens[b]`` of the packed [B, S, W] cache — one contiguous
    W-lane row per sequence; [B, S, H, D] fallback caches take the
    same row write unreshaped).

    Batched scatter (not a one-hot multiply): touches only the written
    rows, so the per-step write cost is O(B*H_kv*D) instead of a full
    cache rewrite — the decode loop's HBM budget is spent on the READ
    sweep only.
    """
    b = cache.shape[0]
    if cache.ndim == 3:
        new_kv = new_kv.reshape(b, -1)
    return cache.at[jnp.arange(b), lens].set(new_kv.astype(cache.dtype))


def init_paged_kv_arena(num_layers, num_blocks, block_len, num_kv_heads,
                        head_dim, dtype):
    """Per-layer (k, v) PAGED block arenas for the serving engine: one
    ``[num_blocks + 1, block_len, ...]`` pool per layer
    (ops/pallas/decode_attention.paged_arena_shape), shared by every
    slot through per-slot block tables.  The extra trailing row is the
    TRASH block: statically-shaped scatters from vacant/frozen slots
    and from pad positions of a prefill chunk are redirected there, so
    a masked write can never touch another sequence's blocks.  Zero
    init matters only for the trash/never-written rows: reads past a
    row's ``lens`` are masked to weight 0, which is exact only against
    finite stale data (0 * NaN = NaN).

    ``dtype="int8"`` selects the QUANTIZED cache: each layer yields a
    4-tuple ``(k_codes, v_codes, k_scales, v_scales)`` — int8 code
    arenas plus parallel ``[num_blocks + 1, block_len, H_kv]`` f32
    absmax-scale arenas (``quantize_kv_heads``); every other dtype
    yields the plain (k, v) pair."""
    from ..ops.pallas.decode_attention import (paged_arena_shape,
                                               paged_scale_shape)
    shape = paged_arena_shape(num_blocks + 1, num_kv_heads, block_len,
                              head_dim)
    if jnp.dtype(dtype) == jnp.int8:
        # quantized arenas carry parallel per-entry per-kv-head absmax
        # scale planes (quantize_kv_heads); the trash row exists in the
        # scale arenas too, for the same masked-write reason.  f32
        # scales: a bf16 scale would stack ~0.4% scale error on top of
        # the int8 step, and the scale planes are 4/D of the codes'
        # bytes — not worth the precision trade.
        sshape = paged_scale_shape(num_blocks + 1, num_kv_heads,
                                   block_len)
        return [(jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                 jnp.zeros(sshape, jnp.float32),
                 jnp.zeros(sshape, jnp.float32))
                for _ in range(num_layers)]
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(num_layers)]


def quantize_kv_heads(kv):
    """Per-entry per-kv-head absmax int8 quantization of K/V planes.

    ``kv`` is any ``[..., H_kv, D]`` stack of head vectors; returns
    ``(codes int8 [..., H_kv, D], scales f32 [..., H_kv])`` with
    ``codes * scales[..., None] ~= kv``.  The scale granularity is the
    quantization design decision of the int8 KV cache (notes.md has the
    full rationale): one absmax scale per WRITTEN ENTRY per kv head —
    every append quantizes exactly what it writes and nothing else, so
    writers stay pure scatters (no read-modify-requantize of
    neighbouring block rows) and a value's dequantized form never
    changes after its write (prefix-cached blocks stay bit-identical,
    spec-decode rewind leaves no requantization residue).  absmax is
    clamped so an all-zero plane (pad tails, zero-init rows) yields a
    tiny finite scale, codes 0 and an exact dequant of 0."""
    f = kv.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(f), axis=-1)
    scales = jnp.maximum(absmax, 1e-8) / 127.0
    codes = jnp.clip(jnp.round(f / scales[..., None]), -127, 127)
    return codes.astype(jnp.int8), scales


def _paged_decode_route(arena, tables, lens):
    """(blk, off) arena coordinates for one [B] decode append at slot
    ``lens[b]``: arena row ``tables[b, lens[b] // L]``, offset
    ``lens[b] % L``.  The SINGLE source of the decode trash-routing
    index math — both the code-arena scatter and its ``_q`` scale-plane
    twin route through here, so the two planes can never desynchronize
    (the arena argument only supplies ``shape[1] == L``; code and scale
    arenas agree on it)."""
    b = tables.shape[0]
    block_len = arena.shape[1]
    blk = tables[jnp.arange(b), lens // block_len]
    off = lens % block_len
    return blk, off


def paged_cache_scatter(arena, tables, lens, new_kv):
    """Write one new [B, H_kv, D] decode entry at each sequence's slot
    ``lens[b]``, routed through its block table
    (``_paged_decode_route``).  Vacant and frozen rows carry all-trash
    tables, so their (repeated) writes land in the trash block instead
    of a block another sequence may now own — the paged replacement for
    the dense engine's "done rows overwrite their own dead row"
    contract.  Same O(B*H_kv*D) batched-scatter cost as
    ``cache_scatter``."""
    blk, off = _paged_decode_route(arena, tables, lens)
    if arena.ndim == 3:
        new_kv = new_kv.reshape(tables.shape[0], -1)
    return arena.at[blk, off].set(new_kv.astype(arena.dtype))


def paged_cache_scatter_q(arena, scales, tables, lens, new_kv):
    """Quantize-on-append twin of ``paged_cache_scatter`` for the int8
    cache: the new [B, H_kv, D] entry is absmax-quantized per kv head
    (``quantize_kv_heads``) and its codes + scales are scattered through
    the block table with the SAME trash-routing discipline (vacant/
    frozen rows carry all-trash tables, so both planes of a masked
    write land in the trash row).  Returns ``(arena, scales)``."""
    codes, s = quantize_kv_heads(new_kv)
    arena = paged_cache_scatter(arena, tables, lens, codes)
    blk, off = _paged_decode_route(arena, tables, lens)
    return arena, scales.at[blk, off].set(s)


def _paged_chunk_route(arena, tables, start, n_valid, c):
    """(blk, off) coordinates for a batch-1 chunk of ``c`` consecutive
    positions ``start .. start+c-1`` through ``tables`` ([1,
    max_blocks]); positions ``>= n_valid`` route to the trash row.  The
    SINGLE source of the chunk trash-routing index math, shared by the
    code-arena scatter and its ``_q`` scale-plane twin."""
    block_len = arena.shape[1]
    trash = arena.shape[0] - 1
    pos = start + jnp.arange(c, dtype=jnp.int32)
    idx = jnp.minimum(pos // block_len, tables.shape[1] - 1)
    blk = jnp.where(pos < n_valid, tables[0, idx], trash)
    off = pos % block_len
    return blk, off


def paged_chunk_scatter(arena, tables, start, n_valid, new_kv):
    """Write a batch-1 prefill chunk's K/V planes ([C, H_kv, D]) at
    global positions ``start .. start+C-1`` through the slot's block
    table (``tables`` is [1, max_blocks]).  Positions ``>= n_valid``
    (the pad tail of the prompt's last chunk) write to the trash row:
    the chunk shape is static, so the scatter always issues C writes
    and masking is done by redirecting the target, never by shrinking
    the shape."""
    c = new_kv.shape[0]
    blk, off = _paged_chunk_route(arena, tables, start, n_valid, c)
    if arena.ndim == 3:
        new_kv = new_kv.reshape(c, -1)
    return arena.at[blk, off].set(new_kv.astype(arena.dtype))


def paged_chunk_scatter_q(arena, scales, tables, start, n_valid, new_kv):
    """Quantize-on-append twin of ``paged_chunk_scatter``: the chunk's
    [C, H_kv, D] planes quantize per position per kv head and both
    codes and scales scatter through the table, pad-tail positions
    (``>= n_valid``) trash-routed in BOTH arenas.  Returns
    ``(arena, scales)``."""
    codes, s = quantize_kv_heads(new_kv)
    arena = paged_chunk_scatter(arena, tables, start, n_valid, codes)
    blk, off = _paged_chunk_route(arena, tables, start, n_valid,
                                  new_kv.shape[0])
    return arena, scales.at[blk, off].set(s)


def paged_verify_scatter(arena, tables, lens, n_valid, new_kv):
    """Write a speculative verify forward's K/V planes ([B, C, H_kv, D])
    at per-row global positions ``lens[b] .. lens[b]+C-1`` through each
    row's block table — the batched generalization of
    ``paged_chunk_scatter``'s multi-position machinery (that one is
    batch-1 with a shared start; this one is per-row starts over the
    decode mix).  Columns ``>= n_valid[b]`` (draft-pad tail, rows not
    in spec mode this step) write to the trash row: the C shape is
    static, so the scatter always issues B*C writes and masking is done
    by redirecting the target.  The ``n_valid`` mask is also the
    rollback guarantee's other half: a draft position can only ever
    land inside its own row's blocks at a slot ``> lens`` that the row
    itself overwrites before its ``lens`` advances past it, so a
    rejected draft's K/V is finite garbage behind the ``lens`` mask,
    never another sequence's data."""
    b, c = new_kv.shape[0], new_kv.shape[1]
    blk, off = _paged_verify_route(arena, tables, lens, n_valid, c)
    if arena.ndim == 3:
        new_kv = new_kv.reshape(b, c, -1)
    return arena.at[blk, off].set(new_kv.astype(arena.dtype))


def _paged_verify_route(arena, tables, lens, n_valid, c):
    """(blk, off) coordinates for a verify forward's per-row spans
    ``lens[b] .. lens[b]+c-1`` through each row's table; columns
    ``>= n_valid[b]`` route to the trash row.  The SINGLE source of the
    verify trash-routing index math, shared by the code-arena scatter
    and its ``_q`` scale-plane twin."""
    block_len = arena.shape[1]
    trash = arena.shape[0] - 1
    pos = lens[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    idx = jnp.minimum(pos // block_len, tables.shape[1] - 1)
    blk = jnp.where(jnp.arange(c, dtype=jnp.int32)[None, :]
                    < n_valid[:, None],
                    jnp.take_along_axis(tables, idx, axis=1), trash)
    off = pos % block_len
    return blk, off


def paged_verify_scatter_q(arena, scales, tables, lens, n_valid, new_kv):
    """Quantize-on-append twin of ``paged_verify_scatter``: the verify
    forward's [B, C, H_kv, D] planes quantize per position per kv head;
    codes and scales scatter with the same per-row trash mask (columns
    ``>= n_valid[b]``), so the rollback guarantee carries over to both
    planes — a rejected draft's codes AND its scales are finite garbage
    behind the ``lens`` mask, overwritten before lens reaches them.
    Returns ``(arena, scales)``."""
    codes, s = quantize_kv_heads(new_kv)
    arena = paged_verify_scatter(arena, tables, lens, n_valid, codes)
    blk, off = _paged_verify_route(arena, tables, lens, n_valid,
                                   new_kv.shape[1])
    return arena, scales.at[blk, off].set(s)


def cache_prefill_write(cache, kv_bshd):
    """Write prompt K/V planes ([B, S, H_kv, D] as produced by the
    prefill attention) into the cache from slot 0."""
    kv = kv_bshd.astype(cache.dtype)
    if cache.ndim == 3:
        b, s = kv.shape[0], kv.shape[1]
        kv = kv.reshape(b, s, -1)
        return jax.lax.dynamic_update_slice(cache, kv, (0, 0, 0))
    return jax.lax.dynamic_update_slice(cache, kv, (0, 0, 0, 0))


def cached_decode_attention(q, k_cache, v_cache, lens):
    """One-token GQA attention over the valid cache prefix.

    q: [B, H_q, D]; k_cache/v_cache: packed [B, S_max, H_kv*D] (or the
    [B, S_max, H_kv, D] fallback for odd geometries); lens: [B] =
    index of the LAST valid slot (the just-written token) — slots
    ``<= lens`` participate.  fp32 logits/softmax accumulation on the
    MXU, output in q.dtype.  On TPU this routes to the fused
    flash-decode Pallas kernel (ops/pallas/decode_attention.py — one
    pass over the cache, prefix-aware streaming; the reference
    ``masked_multihead_attention`` / ``fused_multi_transformer_op.cu``
    role), with an XLA einsum fallback elsewhere.
    """
    from ..ops.pallas.decode_attention import decode_attention
    return decode_attention(q, k_cache, v_cache, lens)


def filter_top_k_top_p(lg, top_k, top_p):
    """Per-row temperature-scaled-logits filtering: dynamic top-k
    (``top_k[b] <= 0`` keeps everything) then nucleus top-p on the
    top-k-filtered distribution (``top_p[b] = 1`` keeps everything).
    One descending sort serves both: each filter keeps a PREFIX of
    sorted order, so the cut is a per-row threshold logit and ties at
    the threshold are kept (the standard over-inclusive tie rule).

    The single implementation of the nucleus prefix/tie rule — the
    whole-batch ``sample_token`` config and the serving engine's
    per-request planes (``inference/sampling.py``) both call it, so
    ``generate()`` and ``ServingEngine`` can never drift apart on
    top-k/top-p semantics."""
    v = lg.shape[-1]
    srt = jnp.sort(lg, axis=-1)[..., ::-1]
    j = jnp.arange(v)
    keep_k = (top_k[..., None] <= 0) | (j < top_k[..., None])
    probs = jax.nn.softmax(jnp.where(keep_k, srt, -jnp.inf), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # smallest prefix with cumulative mass >= p; position 0 always kept
    keep = keep_k & ((cum - probs) < top_p[..., None])
    nkeep = jnp.maximum(keep.sum(-1), 1)
    kth = jnp.take_along_axis(srt, (nkeep - 1)[..., None], axis=-1)
    return jnp.where(lg < kth, -jnp.inf, lg)


def sample_token(logits, key, cfg: GenerationConfig):
    """Greedy argmax or temperature/top-k/top-p categorical.
    logits: [B, V].  Filter order is the conventional warp sequence
    (temperature, then top-k, then nucleus top-p over the already
    top-k-filtered distribution) via :func:`filter_top_k_top_p` with
    the static config broadcast to per-row planes; per-REQUEST planes
    live in ``inference/sampling.py`` — this is the static whole-batch
    config of ``generate()`` / ``LLMPredictor``."""
    if not cfg.do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / jnp.maximum(cfg.temperature, 1e-6)
    if cfg.top_k and cfg.top_k > 0 and cfg.top_p >= 1.0:
        # pure top-k keeps the cheap lax.top_k threshold (same
        # keep-ties-at-kth rule as the full filter, without its
        # whole-vocab sort)
        kth = jax.lax.top_k(lg, cfg.top_k)[0][..., -1:]
        lg = jnp.where(lg < kth, -jnp.inf, lg)
    elif (cfg.top_k and cfg.top_k > 0) or cfg.top_p < 1.0:
        rows = lg.shape[:-1]
        lg = filter_top_k_top_p(
            lg,
            jnp.full(rows, int(cfg.top_k or 0), jnp.int32),
            jnp.full(rows, float(cfg.top_p), jnp.float32))
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def _cast_params(values, dtype):
    dt = jnp.dtype(dtype)
    return [v.astype(dt) if jnp.issubdtype(v.dtype, jnp.floating) else v
            for v in values]


def model_arrays(model):
    """(parameters, buffers) backing a serving model.  Buffers matter:
    int8-converted layers (QuantizedLinearInfer) keep qweight/scales as
    buffers, and baking them as jit constants would bloat and
    de-donate the executable."""
    return list(model.parameters()), list(model.buffers())


def swap_call(params, buffers, p_values, b_values, compute_dtype, fn):
    """Run ``fn()`` with the model's params swapped for traced arrays
    (params cast to the serving dtype once — the hoisted fast-layout
    copy; buffers passed through uncast: int8 weights stay int8 and
    quant scales stay fp32)."""
    if len(params) != len(p_values) or len(buffers) != len(b_values):
        raise RuntimeError(
            f"swap_call structure mismatch: captured {len(params)} params/"
            f"{len(buffers)} buffers but got {len(p_values)}/{len(b_values)} "
            "values — the model was structurally mutated (e.g. "
            "weight_only_quantize) after a generate() program was compiled; "
            "the stale executable cannot be reused")
    pv = _cast_params(p_values, compute_dtype)
    saved_p = [p._value for p in params]
    saved_b = [b._value for b in buffers]
    try:
        for p, a in zip(params, pv):
            p._value = a
        for b, a in zip(buffers, b_values):
            b._value = a
        with _tape.no_grad():
            return fn()
    finally:
        for p, s in zip(params, saved_p):
            p._value = s
        for b, s in zip(buffers, saved_b):
            b._value = s


def decode_scan_body(model, cfg: GenerationConfig):
    """The shared per-token scan body: decode_step -> sample -> EOS mask
    -> lens advance.  carry = (tok, lens, kvs, key, done); emits the
    sampled token.  Used by both GenerationMixin.generate and the
    LLMPredictor serving blocks so their semantics cannot diverge."""
    def body(carry, _):
        tok, lens_c, kvs_c, key_c, done = carry
        logits_t, kvs_c = model.decode_step(tok, lens_c, kvs_c)
        if cfg.do_sample:
            key_t, key_c = jax.random.split(key_c)
        else:
            key_t = key_c
        nxt = sample_token(logits_t, key_t, cfg)
        if cfg.eos_token_id is not None:
            nxt = jnp.where(done, cfg.pad_token_id, nxt)
            done_n = done | (nxt == cfg.eos_token_id)
        else:
            done_n = done
        lens_n = jnp.where(done, lens_c, lens_c + 1)
        return (nxt, lens_n, kvs_c, key_c, done_n), nxt
    return body


def beam_scan_body(model, cfg: GenerationConfig, b, k):
    """Per-token beam-search scan body over a [B*K]-batched KV cache.

    The beam-reorder step — the part greedy decode never exercises — is
    a batched GATHER on every cache buffer (``cache[parent_rows]``),
    exactly the role of the reference's cell-state gather in
    ``python/paddle/nn/decode.py:544`` and the cache reordering of its
    beam serving path.  All shapes static; one fused top-k over
    ``K * vocab`` candidates per step.

    carry = (tok [B*K], lens [B*K], kvs, log_probs [B,K],
    beam_len [B,K], done [B,K]); emits (token [B,K], parent [B,K],
    log_probs [B,K], beam_len [B,K]) per step — the per-step scores let
    a block-serving host truncate the tree mid-block and still score
    consistently (LLMPredictor); unused emits are DCE'd by XLA in the
    single-scan generate path.
    """
    neg_inf = jnp.float32(-1e9)

    def body(carry, _):
        tok, lens_c, kvs_c, lp, blen, done = carry
        logits_t, kvs_c = model.decode_step(tok, lens_c, kvs_c)  # [B*K,V]
        vocab = logits_t.shape[-1]
        step_lp = jax.nn.log_softmax(
            logits_t.astype(jnp.float32), axis=-1).reshape(b, k, vocab)
        if cfg.eos_token_id is not None:
            # finished beams contribute exactly one candidate: EOS at
            # zero added cost (score frozen)
            only_eos = jnp.full((vocab,), neg_inf
                                ).at[cfg.eos_token_id].set(0.0)
            step_lp = jnp.where(done[:, :, None], only_eos[None, None, :],
                                step_lp)
        flat = (lp[:, :, None] + step_lp).reshape(b, k * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, k)                 # [B,K]
        parent = top_idx // vocab
        tok_idx = (top_idx % vocab).astype(jnp.int32)
        rows = (jnp.arange(b)[:, None] * k + parent).reshape(-1)  # [B*K]
        kvs_c = [(kc[rows], vc[rows]) for kc, vc in kvs_c]
        lens_g = lens_c[rows]
        barange = jnp.arange(b)[:, None]
        done_g = done[barange, parent]
        blen_g = blen[barange, parent]
        if cfg.eos_token_id is not None:
            emit = jnp.where(done_g, cfg.pad_token_id, tok_idx)
            done_n = done_g | (tok_idx == cfg.eos_token_id)
        else:
            emit = tok_idx
            done_n = done_g
        lens_n = jnp.where(done_g.reshape(-1), lens_g, lens_g + 1)
        blen_n = blen_g + (~done_g).astype(jnp.int32)
        carry_n = (emit.reshape(-1), lens_n, kvs_c, top_lp, blen_n,
                   done_n)
        return carry_n, (emit, parent.astype(jnp.int32), top_lp, blen_n)
    return body


# single backtrace implementation, shared with nn.functional.gather_tree
from ..nn.functional.decoding import _gather_tree_arrays  # noqa: E402


class GenerationMixin:
    """Adds ``generate`` to a causal LM that implements

    - ``prefill(input_ids, seq_lens, kv_caches) ->
        (last_logits [B, V], kv_caches)``: full-context forward over the
        (right-padded) prompt, writing prompt K/V into the caches.
    - ``decode_step(tokens [B], seq_lens, kv_caches) ->
        (logits [B, V], kv_caches)``: one cached decode step; writes the
        token's K/V at slot ``seq_lens`` and attends over ``<= seq_lens``.
    - ``kv_cache_spec() -> (num_layers, num_kv_heads, head_dim)``.

    The compiled program: cast params -> prefill -> scan(decode_step),
    cached per (prompt shape, max_cache_len, GenerationConfig).
    """

    def _generate_compiled(self, b, s_prompt, max_cache_len,
                           cfg: GenerationConfig, arrays):
        cache = getattr(self, "_generate_exe_cache", None)
        if cache is None:
            cache = self._generate_exe_cache = {}
        params, buffers = arrays
        # The compiled closure captures THESE param/buffer Tensor lists;
        # key on their structure so a structural mutation (e.g.
        # weight_only_quantize swapping Linears for quantized twins, which
        # moves weights from params to buffers) misses the cache instead of
        # silently mis-pairing values in swap_call.
        struct = (tuple(id(p) for p in params),
                  tuple(id(bf) for bf in buffers))
        keyt = (b, s_prompt, max_cache_len, cfg, struct)
        hit = cache.get(keyt)
        if hit is not None:
            return hit
        # Entries traced against a different param/buffer structure are
        # permanently unreachable AND their closures pin the old weight
        # lists on device — evict them instead of leaking executables.
        for stale in [k for k in cache if k[4] != struct]:
            del cache[stale]

        n_layers, hkv, d = self.kv_cache_spec()
        cache_dtype = jnp.dtype(cfg.cache_dtype or cfg.compute_dtype)
        model = self

        def run_greedy_or_sampled(ids, lens, key):
            kvs = init_kv_cache(n_layers, b, max_cache_len, hkv, d,
                                cache_dtype)
            logits, kvs = model.prefill(ids, lens, kvs)
            key0, keyr = (jax.random.split(key)
                          if cfg.do_sample else (key, key))
            tok0 = sample_token(logits, key0, cfg)
            done0 = (jnp.zeros((b,), bool) if cfg.eos_token_id is None
                     else tok0 == cfg.eos_token_id)

            if cfg.max_new_tokens > 1:
                (_, lens_f, _, _, _), rest = jax.lax.scan(
                    decode_scan_body(model, cfg),
                    (tok0, lens, kvs, keyr, done0), None,
                    length=cfg.max_new_tokens - 1)
                toks = jnp.concatenate(
                    [tok0[:, None], rest.T.astype(jnp.int32)], axis=1)
            else:
                toks = tok0[:, None]
                lens_f = lens
            return toks, lens_f + 1  # prompt + emitted

        def run_beam(ids, lens):
            """Prefill once at batch B, expand the caches to B*K rows,
            then scan the beam body; backtrace with gather_tree and pick
            the best beam per batch under the length penalty."""
            k = cfg.num_beams
            kvs = init_kv_cache(n_layers, b, max_cache_len, hkv, d,
                                cache_dtype)
            logits, kvs = model.prefill(ids, lens, kvs)        # [B, V]
            lp0 = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            top_lp, tok0 = jax.lax.top_k(lp0, k)               # [B, K]
            tok0 = tok0.astype(jnp.int32)
            done0 = (jnp.zeros((b, k), bool)
                     if cfg.eos_token_id is None
                     else tok0 == cfg.eos_token_id)
            kvs = [(jnp.repeat(kc, k, axis=0), jnp.repeat(vc, k, axis=0))
                   for kc, vc in kvs]
            lens_bk = jnp.repeat(lens, k, axis=0)              # [B*K]
            blen0 = jnp.ones((b, k), jnp.int32)
            if cfg.max_new_tokens > 1:
                carry = (tok0.reshape(-1), lens_bk, kvs, top_lp, blen0,
                         done0)
                (_, _, _, lp_f, blen_f, _), (toks, parents, _, _) = \
                    jax.lax.scan(beam_scan_body(model, cfg, b, k), carry,
                                 None, length=cfg.max_new_tokens - 1)
                ids_seq = jnp.concatenate([tok0[None], toks], axis=0)
                par_seq = jnp.concatenate(
                    [jnp.tile(jnp.arange(k, dtype=jnp.int32)[None, None],
                              (1, b, 1)), parents], axis=0)
                seqs = _gather_tree_arrays(ids_seq, par_seq)  # [T, B, K]
            else:
                seqs = tok0[None]
                lp_f, blen_f = top_lp, blen0
            if cfg.length_penalty:
                score = lp_f / (blen_f.astype(jnp.float32)
                                ** jnp.float32(cfg.length_penalty))
            else:
                score = lp_f
            best = jnp.argmax(score, axis=-1)                  # [B]
            out = jnp.swapaxes(seqs, 0, 1)                     # [B, T, K]
            toks_best = out[jnp.arange(b), :, best].astype(jnp.int32)
            return toks_best, lens + blen_f[jnp.arange(b), best]

        def pure(p_values, b_values, ids, lens, key):
            def run():
                if cfg.num_beams > 1:
                    return run_beam(ids, lens)
                return run_greedy_or_sampled(ids, lens, key)
            return swap_call(params, buffers, p_values, b_values,
                             cfg.compute_dtype, run)

        compiled = jax.jit(pure)
        cache[keyt] = compiled
        return compiled

    def generate(self, input_ids, seq_lens=None, max_new_tokens=32,
                 do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                 num_beams=1,
                 length_penalty=0.0, eos_token_id=None, pad_token_id=0,
                 max_cache_len=None, compute_dtype="bfloat16",
                 cache_dtype=None, seed=0):
        """Generate ``max_new_tokens`` tokens after the (right-padded)
        prompt ``input_ids [B, S]``; ``seq_lens [B]`` are true prompt
        lengths (default: full S).  Returns a Tensor [B, max_new_tokens]
        of int32 token ids (``pad_token_id`` after EOS).

        Reference analogue: PaddleNLP generate() over the
        fused_multi_transformer decode path; see module docstring for
        the TPU formulation.
        """
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if num_beams < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        if num_beams > 1 and do_sample:
            raise ValueError(
                "num_beams > 1 is greedy beam search; do_sample=True is "
                "not supported together with beams")
        ids = _unwrap(input_ids).astype(jnp.int32)
        b, s = ids.shape
        if seq_lens is None:
            lens = jnp.full((b,), s, jnp.int32)
        else:
            import numpy as np
            lens_np = np.asarray(_unwrap(seq_lens))
            if lens_np.shape != (b,) or (lens_np < 1).any() or \
                    (lens_np > s).any():
                # jit-side gathers clamp out-of-range indices silently
                raise ValueError(
                    f"seq_lens must be [{b}] ints in [1, {s}], got "
                    f"{lens_np.tolist()}")
            lens = jnp.asarray(lens_np, jnp.int32)
        if max_cache_len is None:
            max_cache_len = s + max_new_tokens
        if max_cache_len < s + max_new_tokens:
            raise ValueError(
                f"max_cache_len ({max_cache_len}) < prompt + new tokens "
                f"({s} + {max_new_tokens})")
        cfg = GenerationConfig(
            max_new_tokens=int(max_new_tokens), do_sample=bool(do_sample),
            temperature=float(temperature), top_k=int(top_k),
            top_p=float(top_p),
            num_beams=int(num_beams),
            length_penalty=float(length_penalty),
            eos_token_id=eos_token_id, pad_token_id=int(pad_token_id),
            compute_dtype=str(compute_dtype),
            cache_dtype=None if cache_dtype is None else str(cache_dtype))
        params, buffers = model_arrays(self)
        fn = self._generate_compiled(b, s, int(max_cache_len), cfg,
                                     arrays=(params, buffers))
        key = jax.random.PRNGKey(seed)
        # Decode must never run dropout: force eval for the traced call
        # (LLMPredictor already does model.eval(); the plain generate()
        # entry point gets the same guarantee), restoring modes after.
        saved_modes = [(layer, layer.training)
                       for layer in self.sublayers(include_self=True)]
        try:
            self.eval()
            toks, _ = fn([p._value for p in params],
                         [bf._value for bf in buffers], ids, lens, key)
        finally:
            for layer, mode in saved_modes:
                layer.training = mode
        return Tensor(toks)
