"""Flagship model families (the analogue of PaddleNLP's model zoo entries
named in BASELINE.md: Llama for LLM pretraining, plus GPT/ERNIE-style
encoder)."""

from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion, llama_3_8b_config,
                    llama_3_70b_config, tiny_llama_config)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "llama_3_8b_config",
           "llama_3_70b_config", "tiny_llama_config"]
