"""Flagship model families (the analogue of PaddleNLP's model zoo entries
named in BASELINE.md: Llama for LLM pretraining, plus GPT/ERNIE-style
encoder)."""

from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel,
                    LlamaPretrainingCriterion, llama_3_8b_config,
                    llama_3_70b_config, tiny_llama_config)
from .ernie import (ErnieConfig, ErnieModel, ErnieForSequenceClassification,
                    ErnieForTokenClassification, ErnieForQuestionAnswering,
                    ErnieForPretraining, ErniePretrainingCriterion,
                    ernie_base_config, tiny_ernie_config,
                    BertConfig, BertModel, BertForSequenceClassification,
                    BertForTokenClassification, BertForQuestionAnswering,
                    BertForPretraining)
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM,
                  GPTPretrainingCriterion, gpt2_small_config,
                  gpt3_13b_config, tiny_gpt_config)
from .ocr import (DBNet, DBNetConfig, DBLoss, DBFPN, DBHead, db_postprocess,
                  CRNN, CRNNConfig, CTCHeadLoss, ctc_greedy_decode,
                  PPOCRSystem)

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM",
           "LlamaPretrainingCriterion", "llama_3_8b_config",
           "llama_3_70b_config", "tiny_llama_config",
           "ErnieConfig", "ErnieModel", "ErnieForSequenceClassification",
           "ErnieForTokenClassification", "ErnieForQuestionAnswering",
           "ErnieForPretraining", "ErniePretrainingCriterion",
           "ernie_base_config", "tiny_ernie_config",
           "BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForTokenClassification", "BertForQuestionAnswering",
           "BertForPretraining",
           "GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt2_small_config",
           "gpt3_13b_config", "tiny_gpt_config",
           "DBNet", "DBNetConfig", "DBLoss", "DBFPN", "DBHead",
           "db_postprocess", "CRNN", "CRNNConfig", "CTCHeadLoss",
           "ctc_greedy_decode", "PPOCRSystem"]
