"""Quantized-weight serving: the trace-time code+scale context.

``ServingEngine(weight_dtype="int8"|"int4")`` quantizes every hot
projection weight once at load into an int8 code plane (int4 packs two
codes per byte) plus a per-output-channel f32 scale plane — the PR-5
KV-cache discipline applied to weights.  The serving programs are traced
through the models' unchanged ``decode_step`` / ``chunk_step`` /
``verify_step`` signatures, so — exactly like :mod:`.lora` — the planes
ride a TRACE-TIME context instead of new arguments on every layer: the
program builder binds the traced code/scale values and wraps the model
call in :func:`wquant_context`; the projection sites call
:func:`wq_linear` (the plain ``lin(x)`` fast path outside any context)
to route the matmul through the quantized kernel family.

Composition rules:

* **LoRA stays float.**  Projection sites call ``maybe_lora`` ON TOP of
  ``wq_linear``'s output, so the low-rank delta is computed at full
  activation precision against the quantized base — quantizing the
  per-adapter deltas would re-introduce exactly the per-adapter error
  the kv_int8-style quality gate is meant to bound.
* **Loud failure over silent full-precision.**  When the engine
  quantizes a weight, its slot in the swapped param list is a
  ZERO-SIZE placeholder; any projection site that fails to divert
  through ``wq_linear`` hits a shape error at trace time instead of
  silently streaming a stale float plane.
* Non-projection params (embeddings, norms, lm_head) stay float and
  swap through ``swap_call`` unchanged.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional, Tuple

# the projection sets the serving quantizer targets, per model family;
# the models' quant_projections() surfaces return per-layer dicts keyed
# by these names
QUANT_TARGETS_LLAMA = ("q_proj", "k_proj", "v_proj", "o_proj",
                       "gate_proj", "up_proj", "down_proj")
QUANT_TARGETS_GPT = ("qkv_proj", "out_proj", "fc_in", "fc_out")


class WeightQuantContext:
    """The bound, traced planes for one dispatch:
    ``planes[(layer_idx, target)] = (codes, scales)`` with codes
    ``[K, N]`` int8 (``[K//2, N]`` packed for int4) and scales ``[N]``
    f32; ``bits`` is 8 or 4; ``max_m`` caps the Pallas route at
    decode/verify-sized row counts (prefill-sized M re-streams the
    weight per M-block — the XLA dequant fallback wins there)."""

    __slots__ = ("planes", "bits", "max_m")

    def __init__(self, planes: Dict[Tuple[int, str], Tuple], bits: int,
                 max_m: Optional[int] = 256):
        self.planes = planes
        self.bits = bits
        self.max_m = max_m


# the active trace-time context — module state, not a traced value: it
# is only ever consulted while a serving program builder is tracing
_ACTIVE: Optional[WeightQuantContext] = None


@contextmanager
def wquant_context(ctx: Optional[WeightQuantContext]):
    """Activate a weight-quant context for the duration of a traced
    model call (``None`` = explicit no-op, so builders can wrap
    unconditionally)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = ctx
    try:
        yield
    finally:
        _ACTIVE = prev


def wq_linear(lin, x, target: str, layer_idx: int):
    """Projection-site hook: route ``lin``'s matmul through the
    quantized codes+scales when the active context registers
    ``(layer_idx, target)``; the plain ``lin(x)`` fast path otherwise
    (one global load and a dict probe, trace-time only).  ``x`` and the
    return are ``Tensor``s; the bias (always float) fuses into the
    kernel's f32 epilogue."""
    ctx = _ACTIVE
    if ctx is None:
        return lin(x)
    entry = ctx.planes.get((layer_idx, target))
    if entry is None:
        return lin(x)
    codes, scales = entry
    from ..ops.pallas.quantized_matmul import routed_quantized_matmul
    bias = None if lin.bias is None else lin.bias._value
    y = routed_quantized_matmul(x._value, codes, scales, bits=ctx.bits,
                                bias=bias, max_m=ctx.max_m)
    from ..core.tensor import Tensor
    return Tensor(y)
