"""PP-OCR-style text detection + recognition models (BASELINE config 4:
"PP-OCRv4 det+rec").

Capability analogue of PaddleOCR's DB text detector (MobileNetV3-ish
backbone -> DBFPN neck -> DB head with differentiable binarization,
arXiv:1911.08947) and CRNN/SVTR-style recognizer (conv feature extractor
-> BiLSTM encoder -> CTC head), trained with the framework's own
``F.ctc_loss``.  All forwards are static-shape; the (inherently
data-dependent) box extraction post-process runs on host like the
reference's C++/numpy postprocess ops.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import nn
from ..nn import functional as F
from ..tensor.manipulation import concat, squeeze, transpose


class _ConvBNAct(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, groups=1, act="hardswish"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups,
                              bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self._act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self._act == "hardswish":
            return F.hardswish(x)
        if self._act == "relu":
            return F.relu(x)
        return x


class _DetBackbone(nn.Layer):
    """Compact 4-stage conv backbone emitting {1/4, 1/8, 1/16, 1/32}
    features (the role MobileNetV3 plays in PP-OCR det)."""

    def __init__(self, in_channels=3, scale=0.5):
        super().__init__()
        c = [int(16 * scale * m) for m in (1, 2, 4, 8, 12)]
        self.stem = _ConvBNAct(in_channels, c[0], 3, stride=2)
        self.stage1 = nn.Sequential(_ConvBNAct(c[0], c[1], 3, stride=2),
                                    _ConvBNAct(c[1], c[1], 3))
        self.stage2 = nn.Sequential(_ConvBNAct(c[1], c[2], 3, stride=2),
                                    _ConvBNAct(c[2], c[2], 3))
        self.stage3 = nn.Sequential(_ConvBNAct(c[2], c[3], 3, stride=2),
                                    _ConvBNAct(c[3], c[3], 3))
        self.stage4 = nn.Sequential(_ConvBNAct(c[3], c[4], 3, stride=2),
                                    _ConvBNAct(c[4], c[4], 3))
        self.out_channels = [c[1], c[2], c[3], c[4]]

    def forward(self, x):
        x = self.stem(x)
        c2 = self.stage1(x)
        c3 = self.stage2(c2)
        c4 = self.stage3(c3)
        c5 = self.stage4(c4)
        return c2, c3, c4, c5


class DBFPN(nn.Layer):
    """DB feature pyramid: lateral 1x1 + top-down upsample-add, then each
    level reduced and upsampled to 1/4 scale and concatenated (PaddleOCR
    ppocr/modeling/necks/db_fpn.py)."""

    def __init__(self, in_channels, out_channels=96):
        super().__init__()
        self.out_channels = out_channels
        self.lat = nn.LayerList([
            nn.Conv2D(c, out_channels, 1, bias_attr=False)
            for c in in_channels])
        self.smooth = nn.LayerList([
            nn.Conv2D(out_channels, out_channels // 4, 3, padding=1,
                      bias_attr=False)
            for _ in in_channels])

    def forward(self, feats):
        c2, c3, c4, c5 = feats
        p5 = self.lat[3](c5)
        p4 = self.lat[2](c4) + F.interpolate(p5, scale_factor=2,
                                             mode="nearest")
        p3 = self.lat[1](c3) + F.interpolate(p4, scale_factor=2,
                                             mode="nearest")
        p2 = self.lat[0](c2) + F.interpolate(p3, scale_factor=2,
                                             mode="nearest")
        outs = [self.smooth[0](p2),
                F.interpolate(self.smooth[1](p3), scale_factor=2,
                              mode="nearest"),
                F.interpolate(self.smooth[2](p4), scale_factor=4,
                              mode="nearest"),
                F.interpolate(self.smooth[3](p5), scale_factor=8,
                              mode="nearest")]
        return concat(outs, axis=1)


class DBHead(nn.Layer):
    """Probability + threshold maps; approximate binary map
    B = 1 / (1 + exp(-k (P - T))) (differentiable binarization)."""

    def __init__(self, in_channels, k=50):
        super().__init__()
        self.k = k
        self.prob = self._branch(in_channels)
        self.thresh = self._branch(in_channels)

    @staticmethod
    def _branch(c):
        return nn.Sequential(
            nn.Conv2D(c, c // 4, 3, padding=1, bias_attr=False),
            nn.BatchNorm2D(c // 4), nn.ReLU(),
            nn.Conv2DTranspose(c // 4, c // 4, 2, stride=2),
            nn.BatchNorm2D(c // 4), nn.ReLU(),
            nn.Conv2DTranspose(c // 4, 1, 2, stride=2),
            nn.Sigmoid())

    def forward(self, x):
        p = self.prob(x)
        if not self.training:
            return {"maps": p}
        t = self.thresh(x)
        binary = F.sigmoid(self.k * (p - t))
        return {"maps": concat([p, t, binary], axis=1)}


@dataclass
class DBNetConfig:
    in_channels: int = 3
    backbone_scale: float = 0.5
    fpn_channels: int = 96
    k: int = 50


class DBNet(nn.Layer):
    """DB text detector (det branch of PP-OCR)."""

    def __init__(self, config: DBNetConfig = None):
        super().__init__()
        config = config or DBNetConfig()
        self.backbone = _DetBackbone(config.in_channels,
                                     config.backbone_scale)
        self.neck = DBFPN(self.backbone.out_channels, config.fpn_channels)
        self.head = DBHead(config.fpn_channels, config.k)

    def forward(self, x):
        return self.head(self.neck(self.backbone(x)))


class DBLoss(nn.Layer):
    """BCE on the probability map + L1 on the threshold map + dice on the
    binary map (PaddleOCR DBLoss, weights 5/10/1 simplified)."""

    def __init__(self, alpha=5.0, beta=10.0, eps=1e-6):
        super().__init__()
        self.alpha, self.beta, self.eps = alpha, beta, eps

    def forward(self, preds, gt_prob, gt_thresh=None, gt_mask=None):
        maps = preds["maps"]
        p, t, b = maps[:, 0:1], maps[:, 1:2], maps[:, 2:3]
        bce = F.binary_cross_entropy(p, gt_prob)
        loss = self.alpha * bce
        if gt_thresh is not None:
            loss = loss + self.beta * (t - gt_thresh).abs().mean()
        inter = (b * gt_prob).sum()
        union = b.sum() + gt_prob.sum() + self.eps
        dice = 1.0 - 2.0 * inter / union
        return loss + dice


def db_postprocess(prob_map, bitmap_thresh=0.3, box_thresh=0.6,
                   min_size=3):
    """Extract axis-aligned text boxes from the probability map (host op;
    simplified flood-fill connected components vs the reference's
    pyclipper polygon path)."""
    from ..core.tensor import Tensor
    pm = np.asarray(prob_map._value if isinstance(prob_map, Tensor)
                    else prob_map)
    results = []
    for img in pm[:, 0]:  # [H, W]
        mask = img > bitmap_thresh
        visited = np.zeros_like(mask, bool)
        boxes = []
        h, w = mask.shape
        for sy in range(h):
            for sx in range(w):
                if not mask[sy, sx] or visited[sy, sx]:
                    continue
                stack = [(sy, sx)]
                visited[sy, sx] = True
                ys, xs = [], []
                while stack:
                    y, x = stack.pop()
                    ys.append(y)
                    xs.append(x)
                    for dy, dx in ((1, 0), (-1, 0), (0, 1), (0, -1)):
                        ny, nx = y + dy, x + dx
                        if (0 <= ny < h and 0 <= nx < w and mask[ny, nx]
                                and not visited[ny, nx]):
                            visited[ny, nx] = True
                            stack.append((ny, nx))
                y1, y2 = min(ys), max(ys)
                x1, x2 = min(xs), max(xs)
                if (y2 - y1 + 1) < min_size or (x2 - x1 + 1) < min_size:
                    continue
                score = float(img[y1:y2 + 1, x1:x2 + 1].mean())
                if score >= box_thresh:
                    boxes.append([x1, y1, x2 + 1, y2 + 1, score])
        results.append(np.asarray(boxes, np.float32).reshape(-1, 5))
    return results


# ---------------------------------------------------------------- recognition

@dataclass
class CRNNConfig:
    in_channels: int = 3
    num_classes: int = 97       # charset + blank
    hidden_size: int = 96
    image_height: int = 32


class CRNN(nn.Layer):
    """CRNN recognizer: conv stack (height-collapsing) -> BiLSTM -> CTC
    logits [T, B, num_classes] (PaddleOCR rec_crnn architecture)."""

    def __init__(self, config: CRNNConfig = None):
        super().__init__()
        config = config or CRNNConfig()
        self.config = config
        ch = (64, 128, 256, 256)
        self.convs = nn.Sequential(
            _ConvBNAct(config.in_channels, ch[0], 3, act="relu"),
            nn.MaxPool2D(2, 2),                       # H/2, W/2
            _ConvBNAct(ch[0], ch[1], 3, act="relu"),
            nn.MaxPool2D(2, 2),                       # H/4, W/4
            _ConvBNAct(ch[1], ch[2], 3, act="relu"),
            _ConvBNAct(ch[2], ch[3], 3, act="relu"),
            nn.MaxPool2D((2, 1), (2, 1)),             # H/8, W/4
        )
        feat_h = config.image_height // 8
        self.encoder = nn.LSTM(ch[3] * feat_h, config.hidden_size,
                               direction="bidirect")
        self.fc = nn.Linear(2 * config.hidden_size, config.num_classes)

    def forward(self, x):
        feat = self.convs(x)                     # [B, C, H', W']
        b, c, h, w = feat.shape
        feat = transpose(feat, [0, 3, 1, 2]).reshape([b, w, c * h])
        out, _ = self.encoder(feat)              # [B, W', 2*hidden]
        logits = self.fc(out)                    # [B, T, num_classes]
        return logits


class CTCHeadLoss(nn.Layer):
    """CTC loss over CRNN logits (blank = 0, reference warpctc parity)."""

    def __init__(self, blank: int = 0):
        super().__init__()
        self.blank = blank

    def forward(self, logits, labels, label_lengths):
        # logits [B, T, C] -> log_probs [T, B, C]
        log_probs = F.log_softmax(transpose(logits, [1, 0, 2]), axis=-1)
        t, b = log_probs.shape[0], log_probs.shape[1]
        from ..tensor.creation import full
        input_lengths = full([b], t, dtype="int64")
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction="mean")


def ctc_greedy_decode(logits, blank: int = 0):
    """Greedy CTC decode: argmax per step, collapse repeats, drop blanks.
    Host op (variable-length output)."""
    from ..core.tensor import Tensor
    arr = np.asarray(logits._value if isinstance(logits, Tensor) else logits)
    preds = arr.argmax(axis=-1)  # [B, T]
    out = []
    for seq in preds:
        collapsed = []
        prev = None
        for s in seq:
            if s != prev and s != blank:
                collapsed.append(int(s))
            prev = s
        out.append(collapsed)
    return out


class PPOCRSystem(nn.Layer):
    """det+rec pipeline facade: detect boxes, crop, recognize."""

    def __init__(self, det: DBNet = None, rec: CRNN = None):
        super().__init__()
        self.det = det or DBNet()
        self.rec = rec or CRNN()

    def forward(self, images):
        det_out = self.det(images)
        return det_out

    def recognize_crops(self, crops):
        return self.rec(crops)
