"""Llama model family — the flagship LLM.

Capability analogue of PaddleNLP's Llama implementation driven by the
reference's fleet hybrid-parallel stack (BASELINE configs 3 and 5).
TPU-native design decisions:
- GQA attention through nn.functional.scaled_dot_product_attention
  (Pallas flash kernel on TPU, XLA fallback elsewhere).
- RMSNorm / RoPE / SwiGLU via the incubate fused functionals.
- 4D parallelism is pure annotation: mp layers (Column/Row/VocabParallel)
  carry "model"-axis shardings; batch carries "data"; optimizer states
  shard over "sharding"; the pipe axis is driven by PipelineLayer +
  the pipeline engine.  One model definition serves 1-chip and v5p-64.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..incubate.nn.functional import llama_rope, swiglu
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)
from .generation import GenerationMixin
from .lora import maybe_lora
from .wquant import wq_linear


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    recompute: bool = False
    # jax.checkpoint policy name for recompute ("dots" saves weight-matmul
    # outputs and recomputes attention/elementwise — see
    # distributed/utils._resolve_policy); None = full remat
    recompute_policy: Optional[str] = None
    # apply recompute_policy to every k-th layer, recompute_policy_alt to
    # the rest — a memory/time dial when the stronger policy's saves
    # don't fit HBM for all layers (stride 1 = recompute_policy
    # everywhere)
    recompute_policy_stride: int = 1
    recompute_policy_alt: Optional[str] = None
    # fuse lm_head + cross entropy (chunked over tokens, [N, vocab]
    # logits never materialized — incubate fused_linear_cross_entropy);
    # training-with-labels path only, single-device (TP uses ParallelCE)
    fused_linear_loss: bool = False
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def llama_3_8b_config(**kw):
    return LlamaConfig(vocab_size=128256, hidden_size=4096,
                       intermediate_size=14336, num_hidden_layers=32,
                       num_attention_heads=32, num_key_value_heads=8,
                       rope_theta=500000.0, **kw)


def llama_3_70b_config(**kw):
    return LlamaConfig(vocab_size=128256, hidden_size=8192,
                       intermediate_size=28672, num_hidden_layers=80,
                       num_attention_heads=64, num_key_value_heads=8,
                       rope_theta=500000.0, **kw)


def tiny_llama_config(**kw):
    return LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                       num_hidden_layers=2, num_attention_heads=4,
                       num_key_value_heads=2, max_position_embeddings=128,
                       **kw)


def _linear_cls(config, kind):
    if config.tensor_parallel:
        return ColumnParallelLinear if kind == "col" else RowParallelLinear
    return None


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.config = config
        # which row of the stacked per-layer LoRA arenas this
        # attention's projections read (models/lora.py; inert — a
        # plain Python int — outside an active adapter context)
        self.layer_idx = int(layer_idx)
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        kv_out = self.num_kv_heads * self.head_dim
        if config.tensor_parallel:
            self.q_proj = ColumnParallelLinear(h, h, has_bias=False,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(h, kv_out, has_bias=False,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(h, kv_out, has_bias=False,
                                               gather_output=False)
            self.o_proj = RowParallelLinear(h, h, has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(h, h, bias_attr=False)
            self.k_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.v_proj = nn.Linear(h, kv_out, bias_attr=False)
            self.o_proj = nn.Linear(h, h, bias_attr=False)

    def _o(self, t):
        """Output projection with the per-row LoRA delta (no-op
        outside an adapter context) — the one o_proj site every
        attention path shares.  wq_linear routes the base matmul
        through the quantized codes+scales when a weight-quant context
        is active (models/wquant.py); the LoRA delta rides full-
        precision on top of the quantized base."""
        out = wq_linear(self.o_proj, t, "o_proj", self.layer_idx)
        return maybe_lora(out, t, "o_proj", self.layer_idx)

    def _qkv_rope(self, x, position_ids=None):
        """Project + rotate.  Head counts derive from the projected width
        so tensor-parallel shards (local heads) reshape correctly."""
        b, s, _ = x.shape
        # quantized base matmul (weight-quant serving context, no-op
        # outside it) + per-row LoRA deltas (batched multi-adapter
        # serving, no-op outside an adapter context) — see
        # models/wquant.py and models/lora.py
        q = maybe_lora(wq_linear(self.q_proj, x, "q_proj", self.layer_idx),
                       x, "q_proj", self.layer_idx)
        k = maybe_lora(wq_linear(self.k_proj, x, "k_proj", self.layer_idx),
                       x, "k_proj", self.layer_idx)
        v = maybe_lora(wq_linear(self.v_proj, x, "v_proj", self.layer_idx),
                       x, "v_proj", self.layer_idx)
        hq = q.shape[-1] // self.head_dim
        hkv = k.shape[-1] // self.head_dim
        q = q.reshape([b, s, hq, self.head_dim])
        k = k.reshape([b, s, hkv, self.head_dim])
        v = v.reshape([b, s, hkv, self.head_dim])
        q, k = llama_rope(q, k, rotary_emb_base=self.config.rope_theta,
                          position_ids=position_ids)
        return q, k, v

    def forward(self, x, position_ids=None, attention_mask=None, cache=None):
        b, s, _ = x.shape
        q, k, v = self._qkv_rope(x, position_ids)
        if cache is not None:
            from ..tensor.manipulation import concat
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            is_causal=attention_mask is None)
        # (the "save_attn" remat policy's tags live inside the flash
        # custom_vjp — ops/pallas/flash_attention.py _flash_fwd — where
        # the O and LSE residuals are; a tag here would save a second
        # copy of O)
        out = out.reshape([b, s, -1])
        out = self._o(out)
        return (out, cache) if cache is not None else out

    def prefill(self, x, position_ids=None):
        """Causal forward that also returns the post-RoPE K/V planes
        ([B, S, H_kv, D] arrays) for the generation cache."""
        b, s, _ = x.shape
        q, k, v = self._qkv_rope(x, position_ids)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = self._o(out.reshape([b, s, -1]))
        return out, (k._value, v._value)

    def decode_step(self, x, kv, lens):
        """One cached decode step (the masked_multihead_attention role,
        GQA-aware).  x: [B, 1, hidden]; kv: (k_cache, v_cache) static
        [B, S_max, H_kv*D] buffers, the PAGED 3-tuple
        (k_arena, v_arena, block_tables) used by the serving engine, or
        the quantized PAGED 5-tuple (k_codes, v_codes, k_scales,
        v_scales, block_tables) of the int8 KV cache (quantize on
        append, dequantize in the attention read); lens: [B] write slot
        / last valid index.  Returns (out [B, 1, hidden], updated kv —
        same arity as given)."""
        from ..core.tensor import Tensor
        q, k, v = self._qkv_rope(x, lens[:, None])
        if len(kv) == 5:
            from .generation import paged_cache_scatter_q
            from ..ops.pallas.decode_attention import decode_attention_paged
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_cache_scatter_q(k_arena, k_s, tables,
                                                 lens, k._value[:, 0])
            v_arena, v_s = paged_cache_scatter_q(v_arena, v_s, tables,
                                                 lens, v._value[:, 0])
            out = decode_attention_paged(q._value[:, 0], k_arena, v_arena,
                                         tables, lens,
                                         kv_scales=(k_s, v_s))
            kv = (k_arena, v_arena, k_s, v_s, tables)
        elif len(kv) == 3:
            from .generation import paged_cache_scatter
            from ..ops.pallas.decode_attention import decode_attention_paged
            k_arena, v_arena, tables = kv
            k_arena = paged_cache_scatter(k_arena, tables, lens,
                                          k._value[:, 0])
            v_arena = paged_cache_scatter(v_arena, tables, lens,
                                          v._value[:, 0])
            out = decode_attention_paged(q._value[:, 0], k_arena, v_arena,
                                         tables, lens)
            kv = (k_arena, v_arena, tables)
        else:
            from .generation import cache_scatter, cached_decode_attention
            k_cache, v_cache = kv
            k_cache = cache_scatter(k_cache, lens, k._value[:, 0])
            v_cache = cache_scatter(v_cache, lens, v._value[:, 0])
            out = cached_decode_attention(q._value[:, 0], k_cache, v_cache,
                                          lens)
            kv = (k_cache, v_cache)
        out = self._o(Tensor(out[:, None, :]))
        return out, kv

    def chunk_step(self, x, kv, start, n_valid):
        """One chunked-prefill step over the PAGED cache: x holds C
        prompt tokens of ONE sequence ([1, C, hidden]) at global
        positions ``start .. start+C-1``; K/V are scattered through the
        slot's block table (pad positions ``>= n_valid`` trash-routed)
        and attention runs causally over the full written prefix —
        prefix-cached blocks included, which is how a prefix hit skips
        recomputing the shared leading blocks."""
        from .generation import paged_chunk_scatter, paged_chunk_scatter_q
        from ..ops.pallas.decode_attention import paged_prefix_attention
        b, c, _ = x.shape
        pos = start + jnp.arange(c, dtype=jnp.int32)
        q, k, v = self._qkv_rope(x, pos[None, :])
        if len(kv) == 5:
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_chunk_scatter_q(k_arena, k_s, tables,
                                                 start, n_valid,
                                                 k._value[0])
            v_arena, v_s = paged_chunk_scatter_q(v_arena, v_s, tables,
                                                 start, n_valid,
                                                 v._value[0])
            out = paged_prefix_attention(q._value, k_arena, v_arena,
                                         tables, start.reshape(1),
                                         kv_scales=(k_s, v_s))
            new_kv = (k_arena, v_arena, k_s, v_s, tables)
        else:
            k_arena, v_arena, tables = kv
            k_arena = paged_chunk_scatter(k_arena, tables, start, n_valid,
                                          k._value[0])
            v_arena = paged_chunk_scatter(v_arena, tables, start, n_valid,
                                          v._value[0])
            out = paged_prefix_attention(q._value, k_arena, v_arena,
                                         tables, start.reshape(1))
            new_kv = (k_arena, v_arena, tables)
        from ..core.tensor import Tensor
        out = self._o(Tensor(out.reshape(b, c, -1)))
        return out, new_kv

    def verify_step(self, x, kv, lens, n_valid):
        """One speculative-verify step over the PAGED cache: x holds
        C = K+1 tokens PER row ([B, C, hidden]) — the row's last
        emitted token plus K draft candidates — at per-row global
        positions ``lens[b] .. lens[b]+C-1``.  K/V scatter through each
        row's block table with columns ``>= n_valid[b]`` trash-routed
        (``paged_verify_scatter``), and attention is causal per query
        offset (``decode_attention_paged_multi``), so position c sees
        exactly the prefix sequential decode would have given it."""
        from .generation import (paged_verify_scatter,
                                 paged_verify_scatter_q)
        from ..ops.pallas.decode_attention import \
            decode_attention_paged_multi
        b, c, _ = x.shape
        pos = lens[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
        q, k, v = self._qkv_rope(x, pos)
        if len(kv) == 5:
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_verify_scatter_q(k_arena, k_s, tables,
                                                  lens, n_valid,
                                                  k._value)
            v_arena, v_s = paged_verify_scatter_q(v_arena, v_s, tables,
                                                  lens, n_valid,
                                                  v._value)
            out = decode_attention_paged_multi(q._value, k_arena, v_arena,
                                               tables, lens,
                                               kv_scales=(k_s, v_s))
            new_kv = (k_arena, v_arena, k_s, v_s, tables)
        else:
            k_arena, v_arena, tables = kv
            k_arena = paged_verify_scatter(k_arena, tables, lens, n_valid,
                                           k._value)
            v_arena = paged_verify_scatter(v_arena, tables, lens, n_valid,
                                           v._value)
            out = decode_attention_paged_multi(q._value, k_arena, v_arena,
                                               tables, lens)
            new_kv = (k_arena, v_arena, tables)
        from ..core.tensor import Tensor
        out = self._o(Tensor(out.reshape(b, c, -1)))
        return out, new_kv


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        # which row of the weight-quant plan this MLP's projections
        # read (models/wquant.py; inert outside an active context)
        self.layer_idx = int(layer_idx)
        h, m = config.hidden_size, config.intermediate_size
        if config.tensor_parallel:
            self.gate_proj = ColumnParallelLinear(h, m, has_bias=False,
                                                  gather_output=False)
            self.up_proj = ColumnParallelLinear(h, m, has_bias=False,
                                                gather_output=False)
            self.down_proj = RowParallelLinear(m, h, has_bias=False,
                                               input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(h, m, bias_attr=False)
            self.up_proj = nn.Linear(h, m, bias_attr=False)
            self.down_proj = nn.Linear(m, h, bias_attr=False)

    def forward(self, x):
        from jax.ad_checkpoint import checkpoint_name
        from ..core.tensor import Tensor
        # tagged for the "save_attn_mlp" remat policy: with gate and up
        # outputs saved, backward skips re-running the two big
        # [hidden, intermediate] matmuls (their grads need BOTH)
        g = Tensor(checkpoint_name(
            wq_linear(self.gate_proj, x, "gate_proj",
                      self.layer_idx)._value, "mlp_gate_up"))
        u = Tensor(checkpoint_name(
            wq_linear(self.up_proj, x, "up_proj",
                      self.layer_idx)._value, "mlp_gate_up"))
        su = swiglu(g, u)
        return wq_linear(self.down_proj, su, "down_proj", self.layer_idx)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.self_attn = LlamaAttention(config, layer_idx=layer_idx)
        self.mlp = LlamaMLP(config, layer_idx=layer_idx)
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self._recompute = config.recompute
        stride = max(1, config.recompute_policy_stride)
        self._recompute_policy = (config.recompute_policy
                                  if layer_idx % stride == 0
                                  else config.recompute_policy_alt)

    def _forward_impl(self, x, position_ids=None, attention_mask=None):
        h = x + self.self_attn(self.input_layernorm(x), position_ids,
                               attention_mask)
        return h + self.mlp(self.post_attention_layernorm(h))

    def forward(self, x, position_ids=None, attention_mask=None):
        if self._recompute and self.training:
            from ..distributed.utils import recompute
            return recompute(self._forward_impl, x, position_ids,
                             attention_mask,
                             policy=self._recompute_policy)
        return self._forward_impl(x, position_ids, attention_mask)

    def prefill(self, x, position_ids=None):
        attn_out, kv = self.self_attn.prefill(self.input_layernorm(x),
                                              position_ids)
        h = x + attn_out
        return h + self.mlp(self.post_attention_layernorm(h)), kv

    def decode_step(self, x, kv, lens):
        attn_out, kv = self.self_attn.decode_step(self.input_layernorm(x),
                                                  kv, lens)
        h = x + attn_out
        return h + self.mlp(self.post_attention_layernorm(h)), kv

    def chunk_step(self, x, kv, start, n_valid):
        attn_out, kv = self.self_attn.chunk_step(self.input_layernorm(x),
                                                 kv, start, n_valid)
        h = x + attn_out
        return h + self.mlp(self.post_attention_layernorm(h)), kv

    def verify_step(self, x, kv, lens, n_valid):
        attn_out, kv = self.self_attn.verify_step(
            self.input_layernorm(x), kv, lens, n_valid)
        h = x + attn_out
        return h + self.mlp(self.post_attention_layernorm(h)), kv


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(config.vocab_size,
                                             config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config, layer_idx=i)
             for i in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, position_ids, attention_mask)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=not config.tensor_parallel)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        if config.tie_word_embeddings:
            self.lm_head.weight = self.llama.embed_tokens.weight

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden = self.llama(input_ids, position_ids, attention_mask)
        if labels is not None and self.config.fused_linear_loss and \
                not self.config.tensor_parallel:
            from ..incubate.nn.functional import fused_linear_cross_entropy
            from ..core.tensor import Tensor
            loss = fused_linear_cross_entropy(
                hidden.reshape([-1, hidden.shape[-1]]),
                self.lm_head.weight, labels.reshape([-1]), chunk=1024)
            loss = loss if isinstance(loss, Tensor) else Tensor(loss)
            # keep the (loss, logits) unpacking contract of the standard
            # path; logits are None BY DESIGN here — never materializing
            # them is the point of the fused loss
            return loss, None
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = LlamaPretrainingCriterion(self.config)(logits, labels)
            return loss, logits
        return logits

    def attn_projections(self):
        """Per-layer ``{target: Linear}`` views of the attention
        projections, in layer order — the LoRA surface (adapter merge
        oracle + AdapterStore shape validation; ``models/lora.py``)."""
        return [{"q_proj": l.self_attn.q_proj,
                 "k_proj": l.self_attn.k_proj,
                 "v_proj": l.self_attn.v_proj,
                 "o_proj": l.self_attn.o_proj}
                for l in self.llama.layers]

    def quant_projections(self):
        """Per-layer ``{target: Linear}`` views of every hot projection
        (attention q/k/v/o + MLP gate/up/down), in layer order — the
        weight-quantization surface (``models/wquant.py``).  Embeddings,
        norms and lm_head are deliberately absent: they stay float."""
        return [{"q_proj": l.self_attn.q_proj,
                 "k_proj": l.self_attn.k_proj,
                 "v_proj": l.self_attn.v_proj,
                 "o_proj": l.self_attn.o_proj,
                 "gate_proj": l.mlp.gate_proj,
                 "up_proj": l.mlp.up_proj,
                 "down_proj": l.mlp.down_proj}
                for l in self.llama.layers]

    # -- GenerationMixin surface (models/generation.py; the reference
    # fused_multi_transformer_op.cu decode-serving role) --
    def kv_cache_spec(self):
        return (self.config.num_hidden_layers,
                self.config.num_key_value_heads, self.config.head_dim)

    def prefill(self, ids, lens, kvs):
        """Prompt pass: write prompt K/V into the static caches; return
        the last-valid-position logits only (the [B, S, vocab] logits
        tensor is never materialized — decode needs one row)."""
        import jax
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        from .generation import cache_prefill_write
        b, s = ids.shape
        hidden, new_kvs = self._prefill_hidden(Tensor(ids))
        out_kvs = [(cache_prefill_write(kc, k), cache_prefill_write(vc, v))
                   for (kc, vc), (k, v) in zip(kvs, new_kvs)]
        h = hidden._value
        last = h[jnp.arange(b), lens - 1]                     # [B, hidden]
        logits = self.lm_head(Tensor(last[:, None, :]))._value[:, 0]
        return logits, out_kvs

    def _prefill_hidden(self, x_ids):
        x = self.llama.embed_tokens(x_ids)
        kvs = []
        for layer in self.llama.layers:
            x, kv = layer.prefill(x)
            kvs.append(kv)
        return self.llama.norm(x), kvs

    def decode_step(self, tokens, lens, kvs):
        """One cached decode step over all layers. tokens: [B] int32.
        Each kv entry may be the dense (k, v) pair or the paged
        (k_arena, v_arena, tables) triple — the layers dispatch."""
        from ..core.tensor import Tensor
        x = self.llama.embed_tokens(Tensor(tokens[:, None]))
        new_kvs = []
        for layer, kv in zip(self.llama.layers, kvs):
            x, kv = layer.decode_step(x, kv, lens)
            new_kvs.append(kv)
        x = self.llama.norm(x)
        logits = self.lm_head(x)._value[:, 0]
        return logits, new_kvs

    def prefill_chunk(self, ids, start, n_valid, kvs):
        """One chunked-prefill pass over all layers (paged kv triples):
        ids [1, C] prompt tokens at global positions start..start+C-1;
        ``n_valid`` is the prompt's true length.  Returns the logits at
        prompt position ``n_valid - 1`` — meaningful only on the chunk
        that covers it (the serving engine ignores earlier chunks'
        return) — plus the updated kvs."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        c = ids.shape[1]
        x = self.llama.embed_tokens(Tensor(ids))
        new_kvs = []
        for layer, kv in zip(self.llama.layers, kvs):
            x, kv = layer.chunk_step(x, kv, start, n_valid)
            new_kvs.append(kv)
        h = self.llama.norm(x)._value
        idx = jnp.clip(n_valid - 1 - start, 0, c - 1)
        last = h[0, idx]                                   # [hidden]
        logits = self.lm_head(Tensor(last[None, None, :]))._value[:, 0]
        return logits, new_kvs

    def verify_step(self, tokens, lens, n_valid, kvs):
        """One speculative-verify pass over all layers (paged kv
        triples): tokens [B, C] — each row's last emitted token plus
        its K draft candidates — at per-row global positions
        ``lens[b] + c``.  Returns logits at ALL C positions
        ([B, C, vocab]; C is small, so materializing them is cheap —
        the verifier needs every position's argmax for the longest-
        prefix acceptance rule) plus the updated kvs.  Columns
        ``>= n_valid[b]`` compute trash-routed garbage the engine
        ignores."""
        from ..core.tensor import Tensor
        x = self.llama.embed_tokens(Tensor(tokens))
        new_kvs = []
        for layer, kv in zip(self.llama.layers, kvs):
            x, kv = layer.verify_step(x, kv, lens, n_valid)
            new_kvs.append(kv)
        x = self.llama.norm(x)
        logits = self.lm_head(x)._value                    # [B, C, V]
        return logits, new_kvs


class LlamaPretrainingCriterion(nn.Layer):
    """Shifted-token cross entropy (PaddleNLP parity: criterion computes the
    mean NLL over non-ignored positions)."""

    def __init__(self, config: Optional[LlamaConfig] = None,
                 ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index
        self._parallel = bool(config and config.tensor_parallel)
        if self._parallel:
            self.parallel_ce = ParallelCrossEntropy(
                ignore_index=ignore_index)

    def forward(self, logits, labels):
        if self._parallel:
            losses = self.parallel_ce(logits, labels)
            return losses.mean()
        return F.cross_entropy(logits, labels,
                               ignore_index=self.ignore_index,
                               reduction="mean")
