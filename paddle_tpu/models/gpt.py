"""GPT decoder model family.

Capability analogue of PaddleNLP's `GPTModel` (GPT-2/3 topology: learned
position embeddings, pre-norm decoder blocks, GELU MLP, causal attention).
Supports the same hybrid-parallel hooks as Llama: tensor-parallel linear
layers when `tensor_parallel=True`, recompute per block, and greedy
decoding with KV cache for generation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import nn
from ..nn import functional as F
from ..tensor.creation import arange
from ..tensor.manipulation import concat, unsqueeze
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    tensor_parallel: bool = False
    recompute: bool = False
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gpt2_small_config(**kw):
    return GPTConfig(**kw)


def gpt3_13b_config(**kw):
    return GPTConfig(hidden_size=5120, num_hidden_layers=40,
                     num_attention_heads=40, intermediate_size=20480,
                     max_position_embeddings=2048, **kw)


def tiny_gpt_config(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    return GPTConfig(**kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        if config.tensor_parallel:
            self.qkv_proj = ColumnParallelLinear(h, 3 * h,
                                                 gather_output=False)
            self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, x, attention_mask=None, cache=None):
        b, s, _ = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cache is not None:
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            cache = (k, v)
        # causal whenever q covers the same span as k (full forward, or the
        # prompt step of cached decoding where the cache starts empty); a
        # single-token decode step attends to the whole cache, so no mask.
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            dropout_p=self.dropout_p if self.training else 0.0,
            is_causal=attention_mask is None and k.shape[1] == s)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.out_proj(out)
        return (out, cache) if cache is not None else out


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        if config.tensor_parallel:
            self.fc_in = ColumnParallelLinear(h, m, gather_output=False)
            self.fc_out = RowParallelLinear(m, h, input_is_parallel=True)
        else:
            self.fc_in = nn.Linear(h, m)
            self.fc_out = nn.Linear(m, h)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        return self.dropout(self.fc_out(F.gelu(self.fc_in(x))))


class GPTDecoderLayer(nn.Layer):
    """Pre-norm block: x + attn(ln1(x)); x + mlp(ln2(x))."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self._recompute = config.recompute

    def _forward_impl(self, x, attention_mask=None, cache=None):
        if cache is not None:
            a, cache = self.attn(self.ln_1(x), attention_mask, cache)
        else:
            a = self.attn(self.ln_1(x), attention_mask)
        x = x + self.dropout(a)
        x = x + self.mlp(self.ln_2(x))
        return (x, cache) if cache is not None else x

    def forward(self, x, attention_mask=None, cache=None):
        if self._recompute and self.training and cache is None:
            from ..distributed.utils import recompute
            return recompute(self._forward_impl, x, attention_mask)
        return self._forward_impl(x, attention_mask, cache)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            self.wte = VocabParallelEmbedding(config.vocab_size,
                                              config.hidden_size)
        else:
            self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTDecoderLayer(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                caches=None):
        b, s = input_ids.shape
        if position_ids is None:
            start = 0 if caches is None else caches[0][0].shape[1]
            position_ids = unsqueeze(
                arange(start, start + s, dtype="int64"), 0)
        x = self.drop(self.wte(input_ids) + self.wpe(position_ids))
        new_caches = [] if caches is not None else None
        for i, block in enumerate(self.h):
            if caches is not None:
                x, c = block(x, attention_mask, caches[i])
                new_caches.append(c)
            else:
                x = block(x, attention_mask)
        x = self.ln_f(x)
        return (x, new_caches) if caches is not None else x


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        self.criterion = GPTPretrainingCriterion(config)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None, caches=None):
        if caches is not None:
            hidden, caches = self.gpt(input_ids, position_ids,
                                      attention_mask, caches)
            return self.lm_head(hidden), caches
        hidden = self.gpt(input_ids, position_ids, attention_mask)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = self.criterion(logits, labels)
            return loss, logits
        return logits

    def generate(self, input_ids, max_new_tokens: int = 16):
        """Greedy decode with KV cache (static shapes per step)."""
        from ..tensor.creation import zeros
        b = input_ids.shape[0]
        caches = [(zeros([b, 0, self.config.num_attention_heads,
                          self.config.head_dim]),
                   zeros([b, 0, self.config.num_attention_heads,
                          self.config.head_dim]))
                  for _ in range(self.config.num_hidden_layers)]
        tokens = input_ids
        cur = input_ids
        for _ in range(max_new_tokens):
            logits, caches = self.forward(cur, caches=caches)
            nxt = logits[:, -1].argmax(axis=-1).reshape([b, 1]).astype("int64")
            tokens = concat([tokens, nxt], axis=1)
            cur = nxt
        return tokens


class GPTPretrainingCriterion(nn.Layer):
    def __init__(self, config: Optional[GPTConfig] = None,
                 ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index
        self._parallel = bool(config and config.tensor_parallel)
        if self._parallel:
            self.parallel_ce = ParallelCrossEntropy(
                ignore_index=ignore_index)

    def forward(self, logits, labels):
        if self._parallel:
            return self.parallel_ce(logits, labels).mean()
        return F.cross_entropy(logits, labels,
                               ignore_index=self.ignore_index,
                               reduction="mean")
