"""GPT decoder model family.

Capability analogue of PaddleNLP's `GPTModel` (GPT-2/3 topology: learned
position embeddings, pre-norm decoder blocks, GELU MLP, causal attention).
Supports the same hybrid-parallel hooks as Llama: tensor-parallel linear
layers when `tensor_parallel=True`, recompute per block, and greedy
decoding with KV cache for generation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .. import nn
from ..nn import functional as F
from ..tensor.creation import arange
from ..tensor.manipulation import concat, unsqueeze
from .generation import GenerationMixin
from .wquant import wq_linear
from ..distributed.fleet.meta_parallel.parallel_layers.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    tensor_parallel: bool = False
    recompute: bool = False
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def gpt2_small_config(**kw):
    return GPTConfig(**kw)


def gpt3_13b_config(**kw):
    return GPTConfig(hidden_size=5120, num_hidden_layers=40,
                     num_attention_heads=40, intermediate_size=20480,
                     max_position_embeddings=2048, **kw)


def tiny_gpt_config(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    return GPTConfig(**kw)


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.config = config
        # which row of the weight-quant plan this attention's
        # projections read (models/wquant.py; inert outside a context)
        self.layer_idx = int(layer_idx)
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        if config.tensor_parallel:
            self.qkv_proj = ColumnParallelLinear(h, 3 * h,
                                                 gather_output=False)
            self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(h, 3 * h)
            self.out_proj = nn.Linear(h, h)
        self.dropout_p = config.attention_probs_dropout_prob

    def _qkv(self, x):
        """The one fused-QKV projection site every attention path
        shares — wq_linear routes it through the quantized codes+scales
        when a weight-quant context is active (the fused [h, 3h] weight
        quantizes as one plane)."""
        return wq_linear(self.qkv_proj, x, "qkv_proj", self.layer_idx)

    def _out(self, t):
        return wq_linear(self.out_proj, t, "out_proj", self.layer_idx)

    def forward(self, x, attention_mask=None):
        # (cached decoding lives in prefill/decode_step below — the
        # static-cache GenerationMixin path; the old concat-grow cache
        # was removed with it)
        b, s, _ = x.shape
        qkv = self._qkv(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask,
            dropout_p=self.dropout_p if self.training else 0.0,
            is_causal=attention_mask is None)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self._out(out)
        return out

    def prefill(self, x):
        """Causal forward returning the K/V planes ([B, S, H, D]) for
        the static generation cache (models/generation.py)."""
        b, s, _ = x.shape
        qkv = self._qkv(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = self._out(out.reshape([b, s, -1]))
        return out, (k._value, v._value)

    def decode_step(self, x, kv, lens):
        """One cached decode step (MHA: kv heads == q heads, so the GQA
        grouped attention runs with group size 1).  kv is the dense
        (k_cache, v_cache) pair, the paged (k_arena, v_arena, tables)
        triple, or the quantized paged 5-tuple (k_codes, v_codes,
        k_scales, v_scales, tables) of the int8 KV cache."""
        from ..core.tensor import Tensor
        b = x.shape[0]
        qkv = self._qkv(x).reshape([b, 1, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if len(kv) == 5:
            from .generation import paged_cache_scatter_q
            from ..ops.pallas.decode_attention import decode_attention_paged
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_cache_scatter_q(k_arena, k_s, tables,
                                                 lens, k._value[:, 0])
            v_arena, v_s = paged_cache_scatter_q(v_arena, v_s, tables,
                                                 lens, v._value[:, 0])
            out = decode_attention_paged(q._value[:, 0], k_arena, v_arena,
                                         tables, lens,
                                         kv_scales=(k_s, v_s))
            kv = (k_arena, v_arena, k_s, v_s, tables)
        elif len(kv) == 3:
            from .generation import paged_cache_scatter
            from ..ops.pallas.decode_attention import decode_attention_paged
            k_arena, v_arena, tables = kv
            k_arena = paged_cache_scatter(k_arena, tables, lens,
                                          k._value[:, 0])
            v_arena = paged_cache_scatter(v_arena, tables, lens,
                                          v._value[:, 0])
            out = decode_attention_paged(q._value[:, 0], k_arena, v_arena,
                                         tables, lens)
            kv = (k_arena, v_arena, tables)
        else:
            from .generation import cache_scatter, cached_decode_attention
            k_cache, v_cache = kv
            k_cache = cache_scatter(k_cache, lens, k._value[:, 0])
            v_cache = cache_scatter(v_cache, lens, v._value[:, 0])
            out = cached_decode_attention(q._value[:, 0], k_cache, v_cache,
                                          lens)
            kv = (k_cache, v_cache)
        out = self._out(Tensor(out[:, None, :]))
        return out, kv

    def chunk_step(self, x, kv, start, n_valid):
        """One chunked-prefill step over the paged cache (batch-1 C
        prompt tokens; see LlamaAttention.chunk_step — position ids
        are applied at the model level here, GPT has no RoPE)."""
        from .generation import paged_chunk_scatter, paged_chunk_scatter_q
        from ..ops.pallas.decode_attention import paged_prefix_attention
        from ..core.tensor import Tensor
        b, c, _ = x.shape
        qkv = self._qkv(x).reshape([b, c, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if len(kv) == 5:
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_chunk_scatter_q(k_arena, k_s, tables,
                                                 start, n_valid,
                                                 k._value[0])
            v_arena, v_s = paged_chunk_scatter_q(v_arena, v_s, tables,
                                                 start, n_valid,
                                                 v._value[0])
            out = paged_prefix_attention(q._value, k_arena, v_arena,
                                         tables, start.reshape(1),
                                         kv_scales=(k_s, v_s))
            new_kv = (k_arena, v_arena, k_s, v_s, tables)
        else:
            k_arena, v_arena, tables = kv
            k_arena = paged_chunk_scatter(k_arena, tables, start, n_valid,
                                          k._value[0])
            v_arena = paged_chunk_scatter(v_arena, tables, start, n_valid,
                                          v._value[0])
            out = paged_prefix_attention(q._value, k_arena, v_arena,
                                         tables, start.reshape(1))
            new_kv = (k_arena, v_arena, tables)
        out = self._out(Tensor(out.reshape(b, c, -1)))
        return out, new_kv

    def verify_step(self, x, kv, lens, n_valid):
        """One speculative-verify step over the paged cache: C = K+1
        tokens per row at global positions ``lens[b] + c`` (see
        LlamaAttention.verify_step — positions are applied at the model
        level here, GPT has no RoPE)."""
        from .generation import (paged_verify_scatter,
                                 paged_verify_scatter_q)
        from ..ops.pallas.decode_attention import \
            decode_attention_paged_multi
        from ..core.tensor import Tensor
        b, c, _ = x.shape
        qkv = self._qkv(x).reshape([b, c, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if len(kv) == 5:
            k_arena, v_arena, k_s, v_s, tables = kv
            k_arena, k_s = paged_verify_scatter_q(k_arena, k_s, tables,
                                                  lens, n_valid,
                                                  k._value)
            v_arena, v_s = paged_verify_scatter_q(v_arena, v_s, tables,
                                                  lens, n_valid,
                                                  v._value)
            out = decode_attention_paged_multi(q._value, k_arena, v_arena,
                                               tables, lens,
                                               kv_scales=(k_s, v_s))
            new_kv = (k_arena, v_arena, k_s, v_s, tables)
        else:
            k_arena, v_arena, tables = kv
            k_arena = paged_verify_scatter(k_arena, tables, lens, n_valid,
                                           k._value)
            v_arena = paged_verify_scatter(v_arena, tables, lens, n_valid,
                                           v._value)
            out = decode_attention_paged_multi(q._value, k_arena, v_arena,
                                               tables, lens)
            new_kv = (k_arena, v_arena, tables)
        out = self._out(Tensor(out.reshape(b, c, -1)))
        return out, new_kv


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.layer_idx = int(layer_idx)
        h, m = config.hidden_size, config.intermediate_size
        if config.tensor_parallel:
            self.fc_in = ColumnParallelLinear(h, m, gather_output=False)
            self.fc_out = RowParallelLinear(m, h, input_is_parallel=True)
        else:
            self.fc_in = nn.Linear(h, m)
            self.fc_out = nn.Linear(m, h)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        h = F.gelu(wq_linear(self.fc_in, x, "fc_in", self.layer_idx))
        return self.dropout(wq_linear(self.fc_out, h, "fc_out",
                                      self.layer_idx))


class GPTDecoderLayer(nn.Layer):
    """Pre-norm block: x + attn(ln1(x)); x + mlp(ln2(x))."""

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config, layer_idx=layer_idx)
        self.ln_2 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.mlp = GPTMLP(config, layer_idx=layer_idx)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self._recompute = config.recompute

    def _forward_impl(self, x, attention_mask=None):
        x = x + self.dropout(self.attn(self.ln_1(x), attention_mask))
        x = x + self.mlp(self.ln_2(x))
        return x

    def forward(self, x, attention_mask=None):
        if self._recompute and self.training:
            from ..distributed.utils import recompute
            return recompute(self._forward_impl, x, attention_mask)
        return self._forward_impl(x, attention_mask)

    def prefill(self, x):
        a, kv = self.attn.prefill(self.ln_1(x))
        x = x + self.dropout(a)
        return x + self.mlp(self.ln_2(x)), kv

    def decode_step(self, x, kv, lens):
        a, kv = self.attn.decode_step(self.ln_1(x), kv, lens)
        x = x + self.dropout(a)
        return x + self.mlp(self.ln_2(x)), kv

    def chunk_step(self, x, kv, start, n_valid):
        a, kv = self.attn.chunk_step(self.ln_1(x), kv, start, n_valid)
        x = x + self.dropout(a)
        return x + self.mlp(self.ln_2(x)), kv

    def verify_step(self, x, kv, lens, n_valid):
        a, kv = self.attn.verify_step(self.ln_1(x), kv, lens, n_valid)
        x = x + self.dropout(a)
        return x + self.mlp(self.ln_2(x)), kv


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            self.wte = VocabParallelEmbedding(config.vocab_size,
                                              config.hidden_size)
        else:
            self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTDecoderLayer(config, layer_idx=i)
                               for i in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, attention_mask=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = unsqueeze(arange(0, s, dtype="int64"), 0)
        x = self.drop(self.wte(input_ids) + self.wpe(position_ids))
        for block in self.h:
            x = block(x, attention_mask)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer, GenerationMixin):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
        self.criterion = GPTPretrainingCriterion(config)

    def forward(self, input_ids, position_ids=None, attention_mask=None,
                labels=None):
        hidden = self.gpt(input_ids, position_ids, attention_mask)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = self.criterion(logits, labels)
            return loss, logits
        return logits

    # -- GenerationMixin surface (models/generation.py: static slot
    # cache, ONE compiled dispatch for prefill + the whole decode scan;
    # replaces the old eager concat-grow loop, which recompiled per
    # step under jit and returned prompt+new instead of just new) --
    def generate(self, input_ids, seq_lens=None, max_new_tokens=32, **kw):
        import numpy as np
        s = input_ids.shape[1]
        limit = self.config.max_position_embeddings
        if seq_lens is None:
            max_len = s
        else:
            max_len = int(np.max(np.asarray(
                getattr(seq_lens, "_value", seq_lens))))
        # learned positions: an out-of-table lookup would silently clamp
        # under jit and decode with a repeated position.  Prefill looks
        # up arange(s); the last FED decode token sits at position
        # max_len + max_new_tokens - 2 (ragged right-padded prompts only
        # consume positions up to their true lengths)
        if s > limit or max_len + max_new_tokens - 1 > limit:
            raise ValueError(
                f"generate: positions up to "
                f"{max(s - 1, max_len + max_new_tokens - 2)} exceed "
                f"max_position_embeddings ({limit})")
        return GenerationMixin.generate(self, input_ids,
                                        seq_lens=seq_lens,
                                        max_new_tokens=max_new_tokens,
                                        **kw)

    def quant_projections(self):
        """Per-layer ``{target: Linear}`` views of every hot projection
        (fused qkv + out + MLP fc_in/fc_out), in layer order — the
        weight-quantization surface (``models/wquant.py``)."""
        return [{"qkv_proj": l.attn.qkv_proj,
                 "out_proj": l.attn.out_proj,
                 "fc_in": l.mlp.fc_in,
                 "fc_out": l.mlp.fc_out}
                for l in self.gpt.h]

    def kv_cache_spec(self):
        return (self.config.num_hidden_layers,
                self.config.num_attention_heads, self.config.head_dim)

    def prefill(self, ids, lens, kvs):
        import jax
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        from .generation import cache_prefill_write
        b, s = ids.shape
        pos = unsqueeze(arange(0, s, dtype="int64"), 0)
        x = self.gpt.drop(self.gpt.wte(Tensor(ids)) + self.gpt.wpe(pos))
        out_kvs = []
        for block, (kc, vc) in zip(self.gpt.h, kvs):
            x, (k, v) = block.prefill(x)
            out_kvs.append((cache_prefill_write(kc, k),
                            cache_prefill_write(vc, v)))
        h = self.gpt.ln_f(x)._value
        last = h[jnp.arange(b), lens - 1]
        logits = self.lm_head(Tensor(last[:, None, :]))._value[:, 0]
        return logits, out_kvs

    def decode_step(self, tokens, lens, kvs):
        from ..core.tensor import Tensor
        tok = Tensor(tokens[:, None])
        pos = Tensor(lens[:, None].astype("int32"))
        x = self.gpt.drop(self.gpt.wte(tok) + self.gpt.wpe(pos))
        new_kvs = []
        for block, kv in zip(self.gpt.h, kvs):
            x, kv = block.decode_step(x, kv, lens)
            new_kvs.append(kv)
        x = self.gpt.ln_f(x)
        logits = self.lm_head(x)._value[:, 0]
        return logits, new_kvs

    def prefill_chunk(self, ids, start, n_valid, kvs):
        """One chunked-prefill pass (paged kv triples): ids [1, C] at
        global positions start..start+C-1; learned positions are
        clipped at the table edge for the pad tail (those rows' K/V are
        trash-routed, so the clamp never leaks into a real prefix).
        Returns the logits at prompt position ``n_valid - 1`` plus the
        updated kvs."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        c = ids.shape[1]
        limit = self.config.max_position_embeddings
        pos = jnp.clip(start + jnp.arange(c, dtype=jnp.int32), 0,
                       limit - 1)
        x = self.gpt.drop(self.gpt.wte(Tensor(ids))
                          + self.gpt.wpe(Tensor(pos[None, :])))
        new_kvs = []
        for block, kv in zip(self.gpt.h, kvs):
            x, kv = block.chunk_step(x, kv, start, n_valid)
            new_kvs.append(kv)
        h = self.gpt.ln_f(x)._value
        idx = jnp.clip(n_valid - 1 - start, 0, c - 1)
        last = h[0, idx]
        logits = self.lm_head(Tensor(last[None, None, :]))._value[:, 0]
        return logits, new_kvs

    def verify_step(self, tokens, lens, n_valid, kvs):
        """One speculative-verify pass (paged kv triples): tokens
        [B, C] at per-row global positions ``lens[b] + c``; learned
        positions are clipped at the table edge for the draft-pad tail
        (those columns' K/V are trash-routed, so the clamp never leaks
        into a real prefix).  Returns logits at all C positions
        ([B, C, vocab]) plus the updated kvs."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        c = tokens.shape[1]
        limit = self.config.max_position_embeddings
        pos = jnp.clip(lens[:, None] + jnp.arange(c, dtype=jnp.int32),
                       0, limit - 1)
        x = self.gpt.drop(self.gpt.wte(Tensor(tokens))
                          + self.gpt.wpe(Tensor(pos)))
        new_kvs = []
        for block, kv in zip(self.gpt.h, kvs):
            x, kv = block.verify_step(x, kv, lens, n_valid)
            new_kvs.append(kv)
        x = self.gpt.ln_f(x)
        logits = self.lm_head(x)._value                    # [B, C, V]
        return logits, new_kvs


class GPTPretrainingCriterion(nn.Layer):
    def __init__(self, config: Optional[GPTConfig] = None,
                 ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index
        self._parallel = bool(config and config.tensor_parallel)
        if self._parallel:
            self.parallel_ce = ParallelCrossEntropy(
                ignore_index=ignore_index)

    def forward(self, logits, labels):
        if self._parallel:
            return self.parallel_ce(logits, labels).mean()
        return F.cross_entropy(logits, labels,
                               ignore_index=self.ignore_index,
                               reduction="mean")
