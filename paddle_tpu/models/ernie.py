"""ERNIE / BERT-style encoder model family.

Capability analogue of PaddleNLP's `ErnieModel`/`BertModel` (the BASELINE
"ERNIE-base finetune 1 chip" smoke config).  Built on the framework's own
TransformerEncoder stack; pretraining (MLM + NSP) and finetune heads
(sequence / token classification, QA) match the reference model zoo's
surface.  TPU notes: the whole forward is static-shape (padded seq len),
attention uses the shared scaled_dot_product_attention (Pallas flash path
on TPU), and encoders run in bf16 under AMP with fp32 layernorm.
"""

from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..tensor.creation import arange, zeros_like
from ..tensor.manipulation import unsqueeze


@dataclass
class ErnieConfig:
    vocab_size: int = 18000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    dtype: str = "float32"


def ernie_base_config(**kw):
    return ErnieConfig(**kw)


def tiny_ernie_config(**kw):
    kw.setdefault("vocab_size", 256)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 64)
    return ErnieConfig(**kw)


class ErnieEmbeddings(nn.Layer):
    """word + position + token-type embeddings -> LayerNorm -> dropout."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            seq = input_ids.shape[1]
            position_ids = unsqueeze(arange(0, seq, dtype="int64"), 0)
        if token_type_ids is None:
            token_type_ids = zeros_like(input_ids)
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class ErniePooler(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden_states):
        return F.tanh(self.dense(hidden_states[:, 0]))


def _attention_mask_from_ids(input_ids, pad_token_id, dtype):
    """[b, s] token ids -> additive [b, 1, 1, s] mask (-1e4 at pads)."""
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    ids = input_ids.value if isinstance(input_ids, Tensor) else input_ids
    mask = (ids != pad_token_id).astype(jnp.float32)
    bias = (1.0 - mask)[:, None, None, :] * -1e4
    return Tensor(bias.astype(dtype))


class ErnieModel(nn.Layer):
    """Reference parity: PaddleNLP ErnieModel (embeddings -> N encoder
    layers -> pooled [CLS]); post-norm encoder like BERT."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        encoder_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            act_dropout=0.0, layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(encoder_layer,
                                             config.num_hidden_layers)
        self.pooler = ErniePooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is None:
            attention_mask = _attention_mask_from_ids(
                input_ids, self.config.pad_token_id, "float32")
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        sequence_output = self.encoder(x, attention_mask)
        pooled_output = self.pooler(sequence_output)
        return sequence_output, pooled_output


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.num_classes = num_classes
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        return logits


class ErnieForTokenClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.num_classes = num_classes
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        seq, _ = self.ernie(input_ids, token_type_ids, position_ids,
                            attention_mask)
        logits = self.classifier(self.dropout(seq))
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.num_classes]), labels.reshape([-1]))
            return loss, logits
        return logits


class ErnieForQuestionAnswering(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.classifier = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, _ = self.ernie(input_ids, token_type_ids, position_ids,
                            attention_mask)
        logits = self.classifier(seq)
        start_logits = logits[:, :, 0]
        end_logits = logits[:, :, 1]
        return start_logits, end_logits


class ErniePretrainingHeads(nn.Layer):
    """MLM transform + decoder (tied to word embeddings) and NSP head."""

    def __init__(self, config: ErnieConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.activation = F.gelu if config.hidden_act == "gelu" else F.relu
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       config.layer_norm_eps)
        if embedding_weights is not None:
            # weight tying: decoder kernel is the transposed word embedding.
            # Keep only a bias here; reference the shared Parameter without
            # re-registering it (it already lives under the embedding layer).
            object.__setattr__(self, "_tied", embedding_weights)
            self.decoder_bias = self.create_parameter(
                [config.vocab_size], is_bias=True)
        else:
            object.__setattr__(self, "_tied", None)
            self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.seq_relationship = nn.Linear(config.hidden_size, 2)

    def forward(self, sequence_output, pooled_output):
        x = self.layer_norm(self.activation(self.transform(sequence_output)))
        if self._tied is not None:
            from ..tensor.linalg import matmul
            prediction_scores = matmul(x, self._tied, transpose_y=True) \
                + self.decoder_bias
        else:
            prediction_scores = self.decoder(x)
        seq_relationship_score = self.seq_relationship(pooled_output)
        return prediction_scores, seq_relationship_score


class ErnieForPretraining(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.ernie = ErnieModel(config)
        self.cls = ErniePretrainingHeads(
            config, self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                 attention_mask)
        return self.cls(seq, pooled)


class ErniePretrainingCriterion(nn.Layer):
    """MLM + NSP loss (ignore_index=-100 masks unmasked positions)."""

    def __init__(self, vocab_size: int, ignore_index: int = -100):
        super().__init__()
        self.vocab_size = vocab_size
        self.ignore_index = ignore_index

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None):
        mlm = F.cross_entropy(
            prediction_scores.reshape([-1, self.vocab_size]),
            masked_lm_labels.reshape([-1]),
            ignore_index=self.ignore_index, reduction="mean")
        if next_sentence_labels is None:
            return mlm
        nsp = F.cross_entropy(seq_relationship_score,
                              next_sentence_labels.reshape([-1]),
                              reduction="mean")
        return mlm + nsp


# BERT aliases: the architectures are identical at this capability level;
# PaddleNLP ships both families with the same topology.
BertConfig = ErnieConfig
BertModel = ErnieModel
BertForSequenceClassification = ErnieForSequenceClassification
BertForTokenClassification = ErnieForTokenClassification
BertForQuestionAnswering = ErnieForQuestionAnswering
BertForPretraining = ErnieForPretraining
