"""LoRA math for batched multi-adapter serving: the gathered
(BGMV-style) low-rank delta and the trace-time adapter context.

A LoRA adapter replaces a projection ``y = x W`` with
``y = x W + x A B`` (``A: [d_in, r]``, ``B: [r, d_out]``, the
``alpha / r`` scaling folded into ``B`` at registration).  Serving K
fine-tuned variants of one base model in a single continuous batch
needs that delta PER ROW: row ``b`` of a decode dispatch applies the
adapter its request selected, other rows apply theirs (or none), and
the base matmul ``x W`` stays one shared batched op.  The Punica BGMV
formulation does this with GATHERED einsums over stacked adapter
weights — per-row adapter ids index stacked ``[slots+1, L, d_in, r]``
/ ``[slots+1, L, r, d_out]`` arenas, and two small einsums contract
the gathered stacks:

    h     = einsum('b...i,bir->b...r', x, A_stack[ids][:, layer])
    delta = einsum('b...r,bro->b...o', h, B_stack[ids][:, layer])

The arenas' LAST row is the NULL adapter (all zeros, never written —
the adapter-arena twin of the KV pool's trash row): base-model rows
gather zeros and their delta is an exact ``+ 0.0``, so a mixed batch
leaves base rows' argmax untouched.  Rank is zero-padded to the arena
width, which is exact for the same reason.

**How the delta reaches the model.**  The serving programs are traced
through the models' unchanged ``decode_step`` / ``chunk_step`` /
``verify_step`` signatures, so the per-dispatch adapter planes ride a
TRACE-TIME context instead of new arguments on every layer: the
program builder gathers the stacks from its traced ``lora`` argument
and wraps the model call in :func:`lora_context`; the attention
projections call :func:`maybe_lora` (a no-op outside any context) to
add their row's delta.  The context is plain Python state consulted
during tracing only — training forwards, ``generate()`` and every
non-LoRA serving program never see it and compile byte-identical
programs.

``merged_adapter`` is the parity oracle's tool: it folds ``A @ B``
into the model's projection weights in place (and restores them on
exit), so a per-request ``generate()`` with merged weights is the
"run alone with its adapter" reference the batched gathered path is
asserted token-exact against.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

# the attention projections LoRA targets (the classic q/k/v/o set);
# adapter weight dicts and the AdapterStore arenas are keyed by these
LORA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj")


def attn_lora_dims(config) -> Dict[str, Tuple[int, int]]:
    """``target -> (d_in, d_out)`` for a GQA attention stack described
    by ``config`` (``hidden_size``, ``num_key_value_heads``,
    ``head_dim``) — the shape contract between adapters, the
    AdapterStore arenas and the model's projection hooks."""
    h = int(config.hidden_size)
    kv_out = int(config.num_key_value_heads) * int(config.head_dim)
    return {"q_proj": (h, h), "k_proj": (h, kv_out),
            "v_proj": (h, kv_out), "o_proj": (h, h)}


# the active trace-time context: {target: (Ag, Bg)} with
# Ag [B, L, d_in, r] / Bg [B, L, r, d_out] — already GATHERED per
# dispatch row.  Module state, not a traced value: it is only ever
# consulted while a serving program builder is tracing.
_ACTIVE: Optional[Dict[str, Tuple]] = None


def gather_lora(planes) -> Dict[str, Tuple]:
    """Gather per-row adapter stacks from a dispatch's traced ``lora``
    planes: ``planes = {"ids": [B] int32, "a": {target: arena},
    "b": {target: arena}}`` with arenas ``[slots+1, L, d_in, r]`` /
    ``[slots+1, L, r, d_out]``.  One gather per target per dispatch
    (hoisted out of the decode scan — ids are loop-invariant), sized
    ``B * L * d * r``: the BGMV trade of a small gathered copy for
    per-row weight selection fused into the batched einsum."""
    ids = planes["ids"]
    return {t: (planes["a"][t][ids], planes["b"][t][ids])
            for t in planes["a"]}


@contextmanager
def lora_context(gathered: Optional[Dict[str, Tuple]]):
    """Activate a gathered adapter context for the duration of a traced
    model call (``None`` = explicit no-op, so builders can wrap
    unconditionally)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = gathered
    try:
        yield
    finally:
        _ACTIVE = prev


def lora_delta(target: str, layer_idx: int, x):
    """The gathered low-rank delta for ``target`` at ``layer_idx`` —
    ``x`` is the projection INPUT (``[B, S, d_in]`` raw array), the
    return is ``[B, S, d_out]`` in ``x.dtype`` — or ``None`` when no
    context is active (the non-LoRA fast path: one global load and a
    membership test)."""
    if _ACTIVE is None or target not in _ACTIVE:
        return None
    a_g, b_g = _ACTIVE[target]
    a_l = a_g[:, layer_idx]            # [B, d_in, r]
    b_l = b_g[:, layer_idx]            # [B, r, d_out]
    h = jnp.einsum("b...i,bir->b...r", x, a_l)
    return jnp.einsum("b...r,bro->b...o", h, b_l).astype(x.dtype)


def maybe_lora(out, x, target: str, layer_idx: int):
    """Hook the models' projection sites call: add ``x``'s per-row
    adapter delta to the base projection output ``out`` (both
    ``Tensor``s) when a context is active; return ``out`` unchanged
    otherwise."""
    d = lora_delta(target, layer_idx, x._value)
    if d is None:
        return out
    from ..core.tensor import Tensor
    return Tensor(out._value + d)


def merged_weight_delta(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """``A @ B`` per layer: the dense ``[d_in, d_out]`` weight delta of
    one (already-scaled) adapter layer — what merging folds into the
    base ``Linear.weight`` (reference layout ``[in, out]``)."""
    return np.asarray(a, np.float32) @ np.asarray(b, np.float32)


@contextmanager
def merged_adapter(model, adapter):
    """Fold ``adapter`` into ``model``'s attention projection weights
    in place for the duration of the block, restoring the originals on
    exit — the per-request merged-weights oracle the batched gathered
    path is asserted token-exact against.  ``model`` must expose
    ``attn_projections()`` (a per-layer ``{target: Linear}`` list);
    ``adapter`` carries ``weights[target] = (A [L, d_in, r],
    B [L, r, d_out])`` with scaling folded into B."""
    projs = model.attn_projections()
    saved = []
    try:
        for li, layer_projs in enumerate(projs):
            for t, lin in layer_projs.items():
                if t not in adapter.weights:
                    continue
                a, b = adapter.weights[t]
                saved.append((lin.weight, lin.weight._value))
                delta = merged_weight_delta(a[li], b[li])
                lin.weight._value = lin.weight._value + jnp.asarray(
                    delta, lin.weight._value.dtype)
        yield model
    finally:
        for param, orig in saved:
            param._value = orig
