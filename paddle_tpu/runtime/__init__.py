"""paddle_tpu.runtime — native host runtime services.

C++ components (compiled on demand, ctypes-bound; see native/ptpu_runtime.h
for the reference mapping):

- BlockingQueue: DataLoader prefetch queue (≙ LoDTensorBlockingQueue)
- TCPStore / TCPStoreServer: KV rendezvous (≙ phi TCPStore)
- HostTracer: host profiling events + chrome trace (≙ host_event_recorder)
- stat_*: named current/peak counters (≙ paddle/fluid/memory/stats.h)
- WorkQueue: thread-pool task runner (≙ new_executor workqueue)

Set PTPU_DISABLE_NATIVE=1 to force the pure-Python fallback.
"""

from __future__ import annotations

import os

NATIVE_AVAILABLE = False

if os.environ.get("PTPU_DISABLE_NATIVE") != "1":
    try:
        from .native_bindings import (  # noqa: F401
            BlockingQueue, QueueClosed, TCPStore, TCPStoreServer, HostTracer,
            WorkQueue, now_ns, stat_update, stat_current, stat_peak,
            stat_reset, stat_names,
        )
        NATIVE_AVAILABLE = True
    except Exception as _e:  # pragma: no cover - toolchain missing
        import warnings

        warnings.warn(f"paddle_tpu native runtime unavailable ({_e}); "
                      "using pure-Python fallback")

if not NATIVE_AVAILABLE:
    from ._fallback import (  # noqa: F401
        BlockingQueue, QueueClosed, TCPStore, TCPStoreServer, HostTracer,
        WorkQueue, now_ns, stat_update, stat_current, stat_peak,
        stat_reset, stat_names,
    )

__all__ = [
    "BlockingQueue", "QueueClosed", "TCPStore", "TCPStoreServer",
    "HostTracer", "WorkQueue", "now_ns", "stat_update", "stat_current",
    "stat_peak", "stat_reset", "stat_names", "NATIVE_AVAILABLE",
]
