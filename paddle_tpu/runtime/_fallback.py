"""Pure-Python shims matching runtime.native_bindings when the native
toolchain is unavailable (same public API, reduced fidelity). The native
path is the supported one; this keeps CI/minimal environments working."""

from __future__ import annotations

import queue as _pyqueue
import threading
import time
from typing import Any, Optional


def now_ns() -> int:
    return time.monotonic_ns()


class QueueClosed(Exception):
    pass


class BlockingQueue:
    def __init__(self, capacity: int):
        self._q = _pyqueue.Queue(maxsize=max(capacity, 1))
        self._closed = threading.Event()

    def push(self, obj: Any, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._closed.is_set():
                raise QueueClosed("queue closed")
            try:
                self._q.put(obj, timeout=0.05)
                return True
            except _pyqueue.Full:
                if deadline is not None and time.monotonic() > deadline:
                    return False

    def pop(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self._q.get(timeout=0.05)
            except _pyqueue.Empty:
                if self._closed.is_set() and self._q.empty():
                    raise QueueClosed("queue closed and drained")
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("BlockingQueue.pop timed out")

    def size(self) -> int:
        return self._q.qsize()

    def capacity(self) -> int:
        return self._q.maxsize

    def close(self):
        self._closed.set()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()


class _TracerState(threading.local):
    def __init__(self):
        self.stack = []


_trace_enabled = False
_trace_events: list = []
_trace_mu = threading.Lock()
_trace_tls = _TracerState()


class HostTracer:
    enabled = False  # fast-path mirror, same as the native bindings

    @staticmethod
    def enable():
        global _trace_enabled
        _trace_enabled = True
        HostTracer.enabled = True

    @staticmethod
    def disable():
        global _trace_enabled
        _trace_enabled = False
        HostTracer.enabled = False

    @staticmethod
    def is_enabled() -> bool:
        return _trace_enabled

    @staticmethod
    def begin(name: str):
        if _trace_enabled:
            _trace_tls.stack.append((name, now_ns()))

    @staticmethod
    def end():
        if _trace_enabled and _trace_tls.stack:
            name, t0 = _trace_tls.stack.pop()
            with _trace_mu:
                _trace_events.append(
                    (0, t0, now_ns(), threading.get_ident(), 0, name))

    @staticmethod
    def instant(name: str):
        if _trace_enabled:
            t = now_ns()
            with _trace_mu:
                _trace_events.append(
                    (1, t, t, threading.get_ident(), 0, name))

    @staticmethod
    def counter(name: str, value: int):
        if _trace_enabled:
            t = now_ns()
            with _trace_mu:
                _trace_events.append(
                    (2, t, t, threading.get_ident(), value, name))

    @staticmethod
    def count() -> int:
        with _trace_mu:
            return len(_trace_events)

    @staticmethod
    def clear():
        with _trace_mu:
            _trace_events.clear()

    @staticmethod
    def events() -> list:
        with _trace_mu:
            return list(_trace_events)

    @staticmethod
    def export_chrome_trace(path: str):
        import json
        out = []
        for kind, t0, t1, tid, value, name in HostTracer.events():
            if kind == 0:
                out.append({"name": name, "ph": "X", "pid": 0, "tid": tid,
                            "ts": t0 / 1e3, "dur": (t1 - t0) / 1e3})
            elif kind == 1:
                out.append({"name": name, "ph": "i", "pid": 0, "tid": tid,
                            "ts": t0 / 1e3, "s": "t"})
            else:
                out.append({"name": name, "ph": "C", "pid": 0, "tid": tid,
                            "ts": t0 / 1e3, "args": {"value": value}})
        with open(path, "w") as f:
            json.dump({"traceEvents": out}, f)


_stats: dict = {}
_stats_mu = threading.Lock()


def stat_update(name: str, delta: int):
    with _stats_mu:
        cur, peak = _stats.get(name, (0, 0))
        cur += delta
        _stats[name] = (cur, max(peak, cur))


def stat_current(name: str) -> int:
    with _stats_mu:
        return _stats.get(name, (0, 0))[0]


def stat_peak(name: str) -> int:
    with _stats_mu:
        return _stats.get(name, (0, 0))[1]


def stat_reset(name: str):
    with _stats_mu:
        _stats.pop(name, None)


def stat_names() -> list:
    with _stats_mu:
        return sorted(_stats)


class WorkQueue:
    def __init__(self, num_threads: int):
        self._q: "_pyqueue.Queue" = _pyqueue.Queue()
        self._errors: list = []
        self._mu = threading.Lock()
        self._stop = False
        self._threads = [threading.Thread(target=self._loop, daemon=True)
                         for _ in range(max(num_threads, 1))]
        for t in self._threads:
            t.start()

    def _loop(self):
        while True:
            fn = self._q.get()
            if fn is None:
                self._q.task_done()
                return
            try:
                fn()
            except Exception as e:
                with self._mu:
                    self._errors.append(e)
            finally:
                self._q.task_done()

    def submit(self, fn):
        if self._stop:
            raise RuntimeError("WorkQueue.submit on stopped queue")
        self._q.put(fn)

    def wait_idle(self):
        self._q.join()
        with self._mu:
            if self._errors:
                raise self._errors.pop(0)

    def pending(self) -> int:
        return self._q.unfinished_tasks

    def shutdown(self):
        self._stop = True
        for _ in self._threads:
            self._q.put(None)


# TCPStore fallback: thin wrappers over the native wire protocol are not
# possible without the native lib; provide a socket-based Python server
# compatible enough for single-host tests.
import socket
import socketserver
import struct as _struct


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv = self.server
        f = self.request
        try:
            while True:
                hdr = self._recv(f, 5)
                if hdr is None:
                    return
                op, keylen = hdr[0], _struct.unpack("<I", hdr[1:5])[0]
                key = self._recv(f, keylen) or b""
                (arg,) = _struct.unpack("<Q", self._recv(f, 8))
                if op == 1:
                    val = self._recv(f, arg) if arg else b""
                    with srv.cv:
                        srv.kv[key] = val
                        srv.cv.notify_all()
                    f.sendall(_struct.pack("<q", 0))
                elif op in (2, 4):
                    deadline = None if arg == 0 else time.monotonic() + arg / 1e3
                    with srv.cv:
                        while key not in srv.kv:
                            left = None if deadline is None else deadline - time.monotonic()
                            if left is not None and left <= 0:
                                break
                            srv.cv.wait(timeout=0.05 if left is None else min(left, 0.05))
                        if key not in srv.kv:
                            f.sendall(_struct.pack("<q", -1))
                        elif op == 2:
                            v = srv.kv[key]
                            f.sendall(_struct.pack("<q", len(v)) + v)
                        else:
                            f.sendall(_struct.pack("<q", 0))
                elif op == 3:
                    with srv.cv:
                        cur = 0
                        v = srv.kv.get(key)
                        if v is not None and len(v) == 8:
                            (cur,) = _struct.unpack("<q", v)
                        cur += _struct.unpack("<q", _struct.pack("<Q", arg))[0]
                        srv.kv[key] = _struct.pack("<q", cur)
                        srv.cv.notify_all()
                    f.sendall(_struct.pack("<q", cur))
        except Exception:
            return

    @staticmethod
    def _recv(sock, n):
        data = b""
        while len(data) < n:
            chunk = sock.recv(n - len(data))
            if not chunk:
                return None
            data += chunk
        return data


class TCPStoreServer:
    def __init__(self, port: int = 0):
        self._srv = socketserver.ThreadingTCPServer(("0.0.0.0", port), _Handler)
        self._srv.daemon_threads = True
        self._srv.allow_reuse_address = True
        self._srv.kv = {}
        self._srv.cv = threading.Condition()
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def stop(self):
        self._srv.shutdown()


class TCPStore:
    def __init__(self, host: str, port: int, timeout: float = 60.0):
        deadline = time.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionError(f"TCPStore: cannot reach {host}:{port}")
                time.sleep(0.05)
        self._sock.settimeout(None)  # blocking gets may legitimately wait >5s
        self._mu = threading.Lock()

    def _req(self, op, key: bytes, arg: int, payload: bytes = b""):
        with self._mu:
            msg = bytes([op]) + _struct.pack("<I", len(key)) + key + \
                _struct.pack("<Q", arg & (2**64 - 1)) + payload
            self._sock.sendall(msg)
            status = _Handler._recv(self._sock, 8)
            (st,) = _struct.unpack("<q", status)
            val = b""
            if op == 2 and st >= 0:
                val = _Handler._recv(self._sock, st) or b""
            return st, val

    def set(self, key: str, value: bytes):
        self._req(1, key.encode(), len(value), value)

    def get(self, key: str, timeout: float = 60.0) -> bytes:
        st, val = self._req(2, key.encode(), int(timeout * 1000))
        if st < 0:
            raise TimeoutError(f"TCPStore.get({key!r}) timed out")
        return val

    def add(self, key: str, delta: int = 1) -> int:
        st, _ = self._req(3, key.encode(), delta)
        return st

    def wait(self, key: str, timeout: float = 60.0):
        st, _ = self._req(4, key.encode(), int(timeout * 1000))
        if st != 0:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def close(self):
        try:
            self._sock.close()
        except Exception:
            pass
