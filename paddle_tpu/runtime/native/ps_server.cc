// Parameter server: dense + sparse float tables over TCP.
//
// TPU-native analogue of the reference's brpc parameter server
// (paddle/fluid/distributed/ps/service/brpc_ps_server.h, tables
// paddle/fluid/distributed/ps/table/{memory_dense_table.h,
// memory_sparse_table.h}, update rules sparse_sgd_rule.h): the server owns
// the tables and applies the SGD rule on push (the "accessor" role);
// sparse rows are created on first pull with uniform(-scale, scale) init,
// matching the reference's create-on-miss embedding semantics. One thread
// per connection; tables sharded under a mutex each.
//
// Wire protocol (little-endian), one request per round trip:
//   u8 op | i32 table | u64 n | u64 dim | f64 lr | payload
//     op=1 CREATE_DENSE                 payload: -
//     op=2 CREATE_SPARSE  lr=init_scale payload: u64 seed | u8 rule |
//          f64 eps | u64 max_mem_rows | u32 path_len | path bytes
//          (rule: 0=naive SGD, 1=adagrad per-feature; max_mem_rows>0
//           enables LRU spill-to-disk at `path` — the SSD table,
//           reference ssd_sparse_table.h; rules: sparse_sgd_rule.h)
//     op=3 PULL_DENSE                   payload: -
//     op=4 SET_DENSE                    payload: dim floats
//     op=5 PUSH_DENSE                   payload: dim floats (grad)
//     op=6 PULL_SPARSE                  payload: n u64 keys
//     op=7 PUSH_SPARSE                  payload: n u64 keys, n*dim floats
//     op=8 SPARSE_SIZE                  payload: -     (total keys)
//     op=9 SPARSE_MEM_ROWS              payload: -     (in-memory keys)
//     op=10 CREATE_GRAPH  n=seed        payload: -
//          (graph tables: adjacency lists served with neighbor sampling,
//           reference common_graph_table.h:501)
//     op=11 GRAPH_ADD_EDGES             payload: n u64 src | n u64 dst
//     op=12 GRAPH_SAMPLE  dim=k         payload: n u64 nodes
//          response: n*k u64 neighbors (with replacement; isolated nodes
//          echo themselves — the self-loop convention)
//     op=13 GRAPH_DEGREE                payload: n u64 nodes
//          response: n u64 degrees
//   response: i64 status_or_len | payload (floats / u64)

#include "ptpu_runtime.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cmath>
#include <list>

#include <atomic>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

bool ps_send_all(int fd, const void* data, size_t len) {
  const char* p = (const char*)data;
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= n;
  }
  return true;
}

bool ps_recv_all(int fd, void* data, size_t len) {
  char* p = (char*)data;
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= n;
  }
  return true;
}

struct DenseTable {
  std::mutex mu;
  std::vector<float> data;
};

// Adjacency table with neighbor sampling (reference
// common_graph_table.h:501 / heter_ps/graph_gpu_ps_table.h — the PS side
// of GNN training: trainers pull sampled neighborhoods, features ride the
// existing sparse tables / HBMEmbedding).
struct GraphTable {
  std::mutex mu;
  std::unordered_map<uint64_t, std::vector<uint64_t>> adj;
  std::mt19937_64 rng;
};

struct SparseTable {
  std::mutex mu;
  int64_t dim = 0;
  double init_scale = 0.0;
  uint64_t seed = 0;
  uint8_t rule = 0;        // 0 = naive SGD, 1 = adagrad per-feature
  double eps = 1e-8;       // adagrad epsilon
  size_t max_mem_rows = 0; // 0 = unbounded (no spill)
  std::string spill_path;
  int spill_fd = -1;
  uint64_t spill_end = 0;
  // row storage width: dim weights (+ dim adagrad accumulators)
  size_t width() const { return (size_t)dim * (rule == 1 ? 2 : 1); }

  std::unordered_map<uint64_t, std::vector<float>> rows;
  std::unordered_map<uint64_t, uint64_t> spilled;  // key -> file offset
  // LRU over in-memory keys: front = most recent
  std::list<uint64_t> lru;
  std::unordered_map<uint64_t, std::list<uint64_t>::iterator> lru_pos;

  ~SparseTable() {
    if (spill_fd >= 0) ::close(spill_fd);
  }

  void touch(uint64_t key) {
    auto it = lru_pos.find(key);
    if (it != lru_pos.end()) lru.erase(it->second);
    lru.push_front(key);
    lru_pos[key] = lru.begin();
  }

  void maybe_evict() {
    if (max_mem_rows == 0 || spill_fd < 0) return;
    while (rows.size() > max_mem_rows && !lru.empty()) {
      uint64_t victim = lru.back();
      lru.pop_back();
      lru_pos.erase(victim);
      auto it = rows.find(victim);
      if (it == rows.end()) continue;
      uint64_t off;
      bool new_slot = false;
      auto sp = spilled.find(victim);
      if (sp != spilled.end()) {
        off = sp->second;  // reuse the key's slot
      } else {
        off = spill_end;
        new_slot = true;
      }
      ssize_t want = (ssize_t)(width() * sizeof(float));
      ssize_t wrote = ::pwrite(spill_fd, it->second.data(), (size_t)want,
                               (off_t)off);
      if (wrote != want) {
        // disk full/short write: keep the row in memory rather than
        // silently losing trained values; stop evicting this round
        touch(victim);
        break;
      }
      if (new_slot) {
        spill_end += (uint64_t)want;
        spilled[victim] = off;
      }
      rows.erase(it);
    }
  }

  std::vector<float>& row(uint64_t key) {
    auto it = rows.find(key);
    if (it != rows.end()) {
      touch(key);
      return it->second;
    }
    std::vector<float> v(width(), 0.f);
    auto sp = spilled.find(key);
    bool loaded = false;
    if (sp != spilled.end() && spill_fd >= 0) {
      ssize_t want = (ssize_t)(width() * sizeof(float));
      loaded = ::pread(spill_fd, v.data(), (size_t)want,
                       (off_t)sp->second) == want;
    }
    if (!loaded && sp == spilled.end() && init_scale != 0.0) {
      // per-key deterministic init: same key -> same row on any server
      std::mt19937_64 gen(seed ^ (key * 0x9e3779b97f4a7c15ULL));
      std::uniform_real_distribution<float> dist((float)-init_scale,
                                                 (float)init_scale);
      for (int64_t j = 0; j < dim; ++j) v[(size_t)j] = dist(gen);
    }
    auto& ref = rows.emplace(key, std::move(v)).first->second;
    touch(key);
    maybe_evict();
    return ref;
  }

  size_t total_keys() {
    size_t n = rows.size();
    for (auto& kv : spilled)
      if (rows.find(kv.first) == rows.end()) ++n;
    return n;
  }

  // apply the accessor rule for one pushed gradient row
  void apply(std::vector<float>& r, const float* g, double lr) {
    if (rule == 1) {
      float* w = r.data();
      float* acc = r.data() + dim;
      for (int64_t j = 0; j < dim; ++j) {
        acc[j] += g[j] * g[j];
        w[j] -= (float)(lr * g[j] / (std::sqrt((double)acc[j]) + eps));
      }
    } else {
      for (int64_t j = 0; j < dim; ++j) r[(size_t)j] -= (float)lr * g[j];
    }
  }
};

struct PSServer {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> running{true};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::vector<int> conn_fds;
  std::mutex handlers_mu;

  std::mutex tables_mu;
  std::map<int32_t, std::unique_ptr<DenseTable>> dense;
  std::map<int32_t, std::unique_ptr<SparseTable>> sparse;
  std::map<int32_t, std::unique_ptr<GraphTable>> graph;

  GraphTable* graph_table(int32_t id) {
    std::lock_guard<std::mutex> l(tables_mu);
    auto it = graph.find(id);
    return it == graph.end() ? nullptr : it->second.get();
  }

  DenseTable* dense_table(int32_t id) {
    std::lock_guard<std::mutex> l(tables_mu);
    auto it = dense.find(id);
    return it == dense.end() ? nullptr : it->second.get();
  }
  SparseTable* sparse_table(int32_t id) {
    std::lock_guard<std::mutex> l(tables_mu);
    auto it = sparse.find(id);
    return it == sparse.end() ? nullptr : it->second.get();
  }
};

void ps_reply_status(int fd, int64_t status) {
  ps_send_all(fd, &status, sizeof(status));
}

void ps_handle_conn(PSServer* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  while (s->running.load()) {
    uint8_t op;
    int32_t table;
    uint64_t n, dim;
    double lr;
    if (!ps_recv_all(fd, &op, 1)) break;
    if (!ps_recv_all(fd, &table, 4) || !ps_recv_all(fd, &n, 8) ||
        !ps_recv_all(fd, &dim, 8) || !ps_recv_all(fd, &lr, 8))
      break;
    switch (op) {
      case 1: {  // CREATE_DENSE (idempotent: re-creating an existing
                 // same-dim table keeps its values — a late-joining
                 // worker must not wipe trained state)
        std::lock_guard<std::mutex> l(s->tables_mu);
        auto& t = s->dense[table];
        if (!t) t = std::make_unique<DenseTable>();
        if (t->data.size() != dim) t->data.assign((size_t)dim, 0.f);
        ps_reply_status(fd, 0);
        break;
      }
      case 2: {  // CREATE_SPARSE
        uint64_t seed, max_mem_rows;
        uint8_t rule;
        double eps;
        uint32_t path_len;
        if (!ps_recv_all(fd, &seed, 8) || !ps_recv_all(fd, &rule, 1) ||
            !ps_recv_all(fd, &eps, 8) ||
            !ps_recv_all(fd, &max_mem_rows, 8) ||
            !ps_recv_all(fd, &path_len, 4))
          return;
        std::string path(path_len, '\0');
        if (path_len && !ps_recv_all(fd, path.data(), path_len)) return;
        std::lock_guard<std::mutex> l(s->tables_mu);
        auto& t = s->sparse[table];
        if (!t) t = std::make_unique<SparseTable>();
        {
          std::lock_guard<std::mutex> tl(t->mu);
          bool nonempty = !t->rows.empty() || !t->spilled.empty();
          if (nonempty &&
              ((uint64_t)t->dim != dim || t->rule != rule)) {
            // changing dim/rule would misinterpret existing row storage
            // (adagrad rows are 2*dim wide) — reject reconfiguration
            ps_reply_status(fd, -5);
            break;
          }
        }
        t->dim = (int64_t)dim;
        t->init_scale = lr;  // lr field carries init_scale for op=2
        t->seed = seed;
        t->rule = rule;
        t->eps = eps;
        t->max_mem_rows = (size_t)max_mem_rows;
        t->spill_path = path;
        if (max_mem_rows > 0 && !path.empty() && t->spill_fd < 0) {
          t->spill_fd = ::open(path.c_str(), O_CREAT | O_RDWR | O_TRUNC,
                               0600);
          if (t->spill_fd < 0) {
            ps_reply_status(fd, -4);
            break;
          }
        }
        ps_reply_status(fd, 0);
        break;
      }
      case 3: {  // PULL_DENSE
        DenseTable* t = s->dense_table(table);
        if (!t || t->data.size() != dim) {
          ps_reply_status(fd, -2);
          break;
        }
        std::vector<float> copy;
        {
          std::lock_guard<std::mutex> l(t->mu);
          copy = t->data;
        }
        int64_t len = (int64_t)(copy.size() * sizeof(float));
        ps_send_all(fd, &len, 8);
        ps_send_all(fd, copy.data(), (size_t)len);
        break;
      }
      case 4:    // SET_DENSE
      case 5: {  // PUSH_DENSE (w -= lr * g)
        std::vector<float> buf((size_t)dim);
        if (!ps_recv_all(fd, buf.data(), buf.size() * sizeof(float)))
          return;
        DenseTable* t = s->dense_table(table);
        if (!t || t->data.size() != dim) {
          ps_reply_status(fd, -2);
          break;
        }
        {
          std::lock_guard<std::mutex> l(t->mu);
          if (op == 4) {
            t->data = buf;
          } else {
            for (size_t i = 0; i < buf.size(); ++i)
              t->data[i] -= (float)lr * buf[i];
          }
        }
        ps_reply_status(fd, 0);
        break;
      }
      case 6: {  // PULL_SPARSE
        std::vector<uint64_t> keys((size_t)n);
        if (!ps_recv_all(fd, keys.data(), keys.size() * 8)) return;
        SparseTable* t = s->sparse_table(table);
        if (!t || (uint64_t)t->dim != dim) {
          ps_reply_status(fd, -2);
          break;
        }
        std::vector<float> out((size_t)(n * dim));
        {
          std::lock_guard<std::mutex> l(t->mu);
          for (uint64_t i = 0; i < n; ++i) {
            auto& row = t->row(keys[i]);
            std::memcpy(out.data() + i * dim, row.data(),
                        (size_t)dim * sizeof(float));
          }
        }
        int64_t len = (int64_t)(out.size() * sizeof(float));
        ps_send_all(fd, &len, 8);
        ps_send_all(fd, out.data(), (size_t)len);
        break;
      }
      case 7: {  // PUSH_SPARSE (row -= lr * g)
        std::vector<uint64_t> keys((size_t)n);
        std::vector<float> grads((size_t)(n * dim));
        if (!ps_recv_all(fd, keys.data(), keys.size() * 8)) return;
        if (!ps_recv_all(fd, grads.data(), grads.size() * sizeof(float)))
          return;
        SparseTable* t = s->sparse_table(table);
        if (!t || (uint64_t)t->dim != dim) {
          ps_reply_status(fd, -2);
          break;
        }
        {
          std::lock_guard<std::mutex> l(t->mu);
          for (uint64_t i = 0; i < n; ++i) {
            auto& row = t->row(keys[i]);
            t->apply(row, grads.data() + i * dim, lr);
          }
        }
        ps_reply_status(fd, 0);
        break;
      }
      case 8: {  // SPARSE_SIZE (all keys, spilled included)
        SparseTable* t = s->sparse_table(table);
        if (!t) {
          ps_reply_status(fd, -2);
          break;
        }
        std::lock_guard<std::mutex> l(t->mu);
        ps_reply_status(fd, (int64_t)t->total_keys());
        break;
      }
      case 9: {  // SPARSE_MEM_ROWS (in-memory rows only)
        SparseTable* t = s->sparse_table(table);
        if (!t) {
          ps_reply_status(fd, -2);
          break;
        }
        std::lock_guard<std::mutex> l(t->mu);
        ps_reply_status(fd, (int64_t)t->rows.size());
        break;
      }
      case 10: {  // CREATE_GRAPH (idempotent; n = rng seed)
        std::lock_guard<std::mutex> l(s->tables_mu);
        if (!s->graph.count(table)) {
          auto t = std::make_unique<GraphTable>();
          t->rng.seed(n ? n : 0x9e3779b97f4a7c15ull);
          s->graph[table] = std::move(t);
        }
        ps_reply_status(fd, 0);
        break;
      }
      case 11: {  // GRAPH_ADD_EDGES
        std::vector<uint64_t> src(n), dst(n);
        if (!ps_recv_all(fd, src.data(), n * 8) ||
            !ps_recv_all(fd, dst.data(), n * 8))
          return;
        GraphTable* t = s->graph_table(table);
        if (!t) {
          ps_reply_status(fd, -2);
          break;
        }
        std::lock_guard<std::mutex> l(t->mu);
        for (uint64_t i = 0; i < n; ++i)
          t->adj[src[i]].push_back(dst[i]);
        ps_reply_status(fd, 0);
        break;
      }
      case 12: {  // GRAPH_SAMPLE (dim = k neighbors per node)
        std::vector<uint64_t> nodes(n);
        if (!ps_recv_all(fd, nodes.data(), n * 8))
          return;
        GraphTable* t = s->graph_table(table);
        if (!t) {
          ps_reply_status(fd, -2);
          break;
        }
        std::vector<uint64_t> out(n * dim);
        {
          std::lock_guard<std::mutex> l(t->mu);
          for (uint64_t i = 0; i < n; ++i) {
            auto it = t->adj.find(nodes[i]);
            if (it == t->adj.end() || it->second.empty()) {
              for (uint64_t j = 0; j < dim; ++j)
                out[i * dim + j] = nodes[i];  // isolated: self-loop
            } else {
              const auto& nb = it->second;
              for (uint64_t j = 0; j < dim; ++j)
                out[i * dim + j] = nb[t->rng() % nb.size()];
            }
          }
        }
        ps_reply_status(fd, (int64_t)(out.size() * 8));
        ps_send_all(fd, out.data(), out.size() * 8);
        break;
      }
      case 13: {  // GRAPH_DEGREE
        std::vector<uint64_t> nodes(n);
        if (!ps_recv_all(fd, nodes.data(), n * 8))
          return;
        GraphTable* t = s->graph_table(table);
        if (!t) {
          ps_reply_status(fd, -2);
          break;
        }
        std::vector<uint64_t> out(n);
        {
          std::lock_guard<std::mutex> l(t->mu);
          for (uint64_t i = 0; i < n; ++i) {
            auto it = t->adj.find(nodes[i]);
            out[i] = it == t->adj.end() ? 0 : it->second.size();
          }
        }
        ps_reply_status(fd, (int64_t)(out.size() * 8));
        ps_send_all(fd, out.data(), out.size() * 8);
        break;
      }
      default:
        ps_reply_status(fd, -3);
        break;
    }
  }
  ::close(fd);
}

std::mutex g_ps_mu;
std::map<int64_t, std::unique_ptr<PSServer>> g_ps_servers;
std::map<int64_t, int> g_ps_clients;  // handle -> fd
int64_t g_ps_next = 1;

}  // namespace

extern "C" {

int64_t ptpu_ps_server_start(int port) {
  auto s = std::make_unique<PSServer>();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) return -1;
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (::bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      ::listen(s->listen_fd, 64) != 0) {
    ::close(s->listen_fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);
  PSServer* sp = s.get();
  s->accept_thread = std::thread([sp] {
    while (sp->running.load()) {
      int fd = ::accept(sp->listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      std::lock_guard<std::mutex> l(sp->handlers_mu);
      if (!sp->running.load()) {
        ::close(fd);
        break;
      }
      sp->conn_fds.push_back(fd);
      sp->handlers.emplace_back(ps_handle_conn, sp, fd);
    }
  });
  std::lock_guard<std::mutex> l(g_ps_mu);
  int64_t h = g_ps_next++;
  g_ps_servers[h] = std::move(s);
  return h;
}

int ptpu_ps_server_port(int64_t h) {
  std::lock_guard<std::mutex> l(g_ps_mu);
  auto it = g_ps_servers.find(h);
  return it == g_ps_servers.end() ? -1 : it->second->port;
}

void ptpu_ps_server_stop(int64_t h) {
  std::unique_ptr<PSServer> s;
  {
    std::lock_guard<std::mutex> l(g_ps_mu);
    auto it = g_ps_servers.find(h);
    if (it == g_ps_servers.end()) return;
    s = std::move(it->second);
    g_ps_servers.erase(it);
  }
  s->running.store(false);
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  // wake every handler (shutdown makes their recv return 0) and JOIN
  // them before the server object is destroyed — a detached handler
  // would dereference freed memory on its next request
  {
    std::lock_guard<std::mutex> l(s->handlers_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->handlers)
    if (t.joinable()) t.join();
}

int64_t ptpu_ps_client_create(const char* host, int port, double timeout_s) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  snprintf(portstr, sizeof(portstr), "%d", port);
  if (getaddrinfo(host, portstr, &hints, &res) != 0 || !res) return -1;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0 || ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    freeaddrinfo(res);
    if (fd >= 0) ::close(fd);
    return -1;
  }
  freeaddrinfo(res);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (timeout_s > 0) {
    timeval tv;
    tv.tv_sec = (time_t)timeout_s;
    tv.tv_usec = (suseconds_t)((timeout_s - (double)tv.tv_sec) * 1e6);
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }
  std::lock_guard<std::mutex> l(g_ps_mu);
  int64_t h = g_ps_next++;
  g_ps_clients[h] = fd;
  return h;
}

void ptpu_ps_client_destroy(int64_t h) {
  std::lock_guard<std::mutex> l(g_ps_mu);
  auto it = g_ps_clients.find(h);
  if (it == g_ps_clients.end()) return;
  ::close(it->second);
  g_ps_clients.erase(it);
}

namespace {

int ps_client_fd(int64_t h) {
  std::lock_guard<std::mutex> l(g_ps_mu);
  auto it = g_ps_clients.find(h);
  return it == g_ps_clients.end() ? -1 : it->second;
}

bool ps_send_header(int fd, uint8_t op, int32_t table, uint64_t n,
                    uint64_t dim, double lr) {
  return ps_send_all(fd, &op, 1) && ps_send_all(fd, &table, 4) &&
         ps_send_all(fd, &n, 8) && ps_send_all(fd, &dim, 8) &&
         ps_send_all(fd, &lr, 8);
}

int64_t ps_recv_status(int fd) {
  int64_t st = -9;
  if (!ps_recv_all(fd, &st, 8)) return -9;
  return st;
}

}  // namespace

int ptpu_ps_create_dense(int64_t c, int32_t table, int64_t dim) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 1, table, 0, (uint64_t)dim, 0.0)) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_create_sparse(int64_t c, int32_t table, int64_t dim,
                          double init_scale, uint64_t seed, uint8_t rule,
                          double eps, uint64_t max_mem_rows,
                          const char* spill_path) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 2, table, 0, (uint64_t)dim, init_scale))
    return PTPU_ERR;
  uint32_t path_len =
      spill_path ? (uint32_t)strlen(spill_path) : 0;
  if (!ps_send_all(fd, &seed, 8) || !ps_send_all(fd, &rule, 1) ||
      !ps_send_all(fd, &eps, 8) || !ps_send_all(fd, &max_mem_rows, 8) ||
      !ps_send_all(fd, &path_len, 4))
    return PTPU_ERR;
  if (path_len && !ps_send_all(fd, spill_path, path_len)) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int64_t ptpu_ps_sparse_mem_rows(int64_t c, int32_t table) {
  int fd = ps_client_fd(c);
  if (fd < 0) return -1;
  if (!ps_send_header(fd, 9, table, 0, 0, 0.0)) return -1;
  return ps_recv_status(fd);
}

int ptpu_ps_pull_dense(int64_t c, int32_t table, float* out, int64_t dim) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 3, table, 0, (uint64_t)dim, 0.0)) return PTPU_ERR;
  int64_t len = ps_recv_status(fd);
  if (len != dim * (int64_t)sizeof(float)) return PTPU_ERR;
  return ps_recv_all(fd, out, (size_t)len) ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_set_dense(int64_t c, int32_t table, const float* val,
                      int64_t dim) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 4, table, 0, (uint64_t)dim, 0.0)) return PTPU_ERR;
  if (!ps_send_all(fd, val, (size_t)dim * sizeof(float))) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_push_dense(int64_t c, int32_t table, const float* grad,
                       int64_t dim, double lr) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 5, table, 0, (uint64_t)dim, lr)) return PTPU_ERR;
  if (!ps_send_all(fd, grad, (size_t)dim * sizeof(float))) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_pull_sparse(int64_t c, int32_t table, const uint64_t* keys,
                        int64_t n, int64_t dim, float* out) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 6, table, (uint64_t)n, (uint64_t)dim, 0.0))
    return PTPU_ERR;
  if (!ps_send_all(fd, keys, (size_t)n * 8)) return PTPU_ERR;
  int64_t len = ps_recv_status(fd);
  if (len != n * dim * (int64_t)sizeof(float)) return PTPU_ERR;
  return ps_recv_all(fd, out, (size_t)len) ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_push_sparse(int64_t c, int32_t table, const uint64_t* keys,
                        int64_t n, int64_t dim, const float* grads,
                        double lr) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 7, table, (uint64_t)n, (uint64_t)dim, lr))
    return PTPU_ERR;
  if (!ps_send_all(fd, keys, (size_t)n * 8)) return PTPU_ERR;
  if (!ps_send_all(fd, grads, (size_t)(n * dim) * sizeof(float)))
    return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int64_t ptpu_ps_sparse_size(int64_t c, int32_t table) {
  int fd = ps_client_fd(c);
  if (fd < 0) return -1;
  if (!ps_send_header(fd, 8, table, 0, 0, 0.0)) return -1;
  return ps_recv_status(fd);
}

int ptpu_ps_create_graph(int64_t c, int32_t table, uint64_t seed) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 10, table, seed, 0, 0.0)) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_graph_add_edges(int64_t c, int32_t table, const uint64_t* src,
                            const uint64_t* dst, int64_t n) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 11, table, (uint64_t)n, 0, 0.0)) return PTPU_ERR;
  if (!ps_send_all(fd, src, (size_t)n * 8)) return PTPU_ERR;
  if (!ps_send_all(fd, dst, (size_t)n * 8)) return PTPU_ERR;
  return ps_recv_status(fd) == 0 ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_graph_sample(int64_t c, int32_t table, const uint64_t* nodes,
                         int64_t n, int64_t k, uint64_t* out) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 12, table, (uint64_t)n, (uint64_t)k, 0.0))
    return PTPU_ERR;
  if (!ps_send_all(fd, nodes, (size_t)n * 8)) return PTPU_ERR;
  int64_t len = ps_recv_status(fd);
  if (len != n * k * 8) return PTPU_ERR;
  return ps_recv_all(fd, out, (size_t)len) ? PTPU_OK : PTPU_ERR;
}

int ptpu_ps_graph_degree(int64_t c, int32_t table, const uint64_t* nodes,
                         int64_t n, uint64_t* out) {
  int fd = ps_client_fd(c);
  if (fd < 0) return PTPU_ERR;
  if (!ps_send_header(fd, 13, table, (uint64_t)n, 0, 0.0)) return PTPU_ERR;
  if (!ps_send_all(fd, nodes, (size_t)n * 8)) return PTPU_ERR;
  int64_t len = ps_recv_status(fd);
  if (len != n * 8) return PTPU_ERR;
  return ps_recv_all(fd, out, (size_t)len) ? PTPU_OK : PTPU_ERR;
}

}  // extern "C"
