// TCPStore: key-value rendezvous over TCP sockets.
//
// TPU-native equivalent of the reference's bootstrap store
// (paddle/phi/core/distributed/store/tcp_store.h:120, tcp_utils.cc):
// one rank runs the master (server thread + per-connection handler
// threads over a mutex-guarded map with a condvar for blocking gets);
// every rank connects a client. Used by paddle_tpu.distributed for
// process-group bootstrap and barriers on multi-host CPU/TPU pods where
// jax.distributed is not already managing coordination.
//
// Wire protocol (little-endian):
//   request:  u8 op | u32 keylen | key bytes | u64 arg | payload
//     op=1 SET   arg=vallen, payload=value
//     op=2 GET   arg=timeout_ms (blocks until key exists)
//     op=3 ADD   arg=(i64)delta
//     op=4 WAIT  arg=timeout_ms
//   response: i64 status_or_len | payload
//     SET -> 0 | GET -> len,value or -1 timeout | ADD -> new value
//     WAIT -> 0 or -1

#include "ptpu_runtime.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

bool send_all(int fd, const void* data, size_t len) {
  const char* p = (const char*)data;
  while (len > 0) {
    ssize_t n = ::send(fd, p, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    p += n;
    len -= n;
  }
  return true;
}

bool recv_all(int fd, void* data, size_t len) {
  char* p = (char*)data;
  while (len > 0) {
    ssize_t n = ::recv(fd, p, len, 0);
    if (n <= 0) return false;
    p += n;
    len -= n;
  }
  return true;
}

struct StoreServer {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  std::vector<std::thread> handlers;
  std::vector<int> conn_fds;
  std::mutex handlers_mu;

  std::map<std::string, std::string> kv;
  std::mutex mu;
  std::condition_variable cv;

  void handle(int fd) {
    while (!stopping.load()) {
      uint8_t op;
      uint32_t keylen;
      uint64_t arg;
      if (!recv_all(fd, &op, 1) || !recv_all(fd, &keylen, 4)) break;
      std::string key(keylen, '\0');
      if (keylen && !recv_all(fd, &key[0], keylen)) break;
      if (!recv_all(fd, &arg, 8)) break;
      int64_t status = 0;
      std::string value;
      if (op == 1) {  // SET
        std::string val(arg, '\0');
        if (arg && !recv_all(fd, &val[0], arg)) break;
        {
          std::lock_guard<std::mutex> l(mu);
          kv[key] = std::move(val);
        }
        cv.notify_all();
        status = 0;
      } else if (op == 2 || op == 4) {  // GET / WAIT
        std::unique_lock<std::mutex> l(mu);
        auto pred = [&] { return stopping.load() || kv.count(key) > 0; };
        bool ok;
        if (arg == 0) {
          cv.wait(l, pred);
          ok = true;
        } else {
          ok = cv.wait_for(l, std::chrono::milliseconds(arg), pred);
        }
        if (!ok || stopping.load() || !kv.count(key)) {
          status = -1;
        } else if (op == 2) {
          value = kv[key];
          status = (int64_t)value.size();
        } else {
          status = 0;
        }
      } else if (op == 3) {  // ADD
        std::lock_guard<std::mutex> l(mu);
        int64_t cur = 0;
        auto it = kv.find(key);
        if (it != kv.end() && it->second.size() == 8) {
          memcpy(&cur, it->second.data(), 8);
        }
        cur += (int64_t)arg;
        std::string val(8, '\0');
        memcpy(&val[0], &cur, 8);
        kv[key] = std::move(val);
        cv.notify_all();
        status = cur;
      } else {
        status = -2;
      }
      if (!send_all(fd, &status, 8)) break;
      if (op == 2 && status >= 0) {
        if (!send_all(fd, value.data(), value.size())) break;
      }
    }
    ::close(fd);
  }

  bool start(int want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(want_port);
    if (::bind(listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(listen_fd);
      return false;
    }
    socklen_t alen = sizeof(addr);
    getsockname(listen_fd, (sockaddr*)&addr, &alen);
    port = ntohs(addr.sin_port);
    if (::listen(listen_fd, 128) != 0) {
      ::close(listen_fd);
      return false;
    }
    accept_thread = std::thread([this] {
      for (;;) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) break;  // listen_fd closed on stop
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        std::lock_guard<std::mutex> l(handlers_mu);
        conn_fds.push_back(fd);
        handlers.emplace_back([this, fd] { handle(fd); });
      }
    });
    return true;
  }

  // Joins every handler thread before returning, so destroying the server
  // afterwards is safe (no detached thread can still reference *this).
  // Handlers wake via stopping+cv (blocking gets) and via shutdown() on
  // their connection fd (blocked recvs); each handler closes its own fd.
  void stop() {
    stopping.store(true);
    cv.notify_all();
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
      listen_fd = -1;
    }
    if (accept_thread.joinable()) accept_thread.join();
    std::lock_guard<std::mutex> l(handlers_mu);
    for (int fd : conn_fds) ::shutdown(fd, SHUT_RDWR);
    for (auto& t : handlers)
      if (t.joinable()) t.join();
    handlers.clear();
    conn_fds.clear();
  }
};

struct StoreClient {
  int fd = -1;
  std::mutex mu;  // serialize request/response pairs

  bool connect_to(const char* host, int port, double timeout_s) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host, port_s.c_str(), &hints, &res) != 0) return false;
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration<double>(timeout_s <= 0 ? 300 : timeout_s);
    bool ok = false;
    while (!ok && std::chrono::steady_clock::now() < deadline) {
      fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        ok = true;
        break;
      }
      if (fd >= 0) ::close(fd);
      fd = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    freeaddrinfo(res);
    if (ok) {
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    return ok;
  }

  // returns status; fills out with GET payload
  int64_t request(uint8_t op, const std::string& key, uint64_t arg,
                  const uint8_t* payload, size_t paylen, std::string* out) {
    std::lock_guard<std::mutex> l(mu);
    uint32_t keylen = key.size();
    if (!send_all(fd, &op, 1) || !send_all(fd, &keylen, 4) ||
        !send_all(fd, key.data(), keylen) || !send_all(fd, &arg, 8))
      return -2;
    if (paylen && !send_all(fd, payload, paylen)) return -2;
    int64_t status;
    if (!recv_all(fd, &status, 8)) return -2;
    if (op == 2 && status >= 0 && out) {
      out->resize(status);
      if (status && !recv_all(fd, &(*out)[0], status)) return -2;
    }
    return status;
  }
};

std::mutex g_mu;
std::unordered_map<int64_t, std::shared_ptr<StoreServer>> g_servers;
std::unordered_map<int64_t, std::shared_ptr<StoreClient>> g_clients;
int64_t g_next = 1;

std::shared_ptr<StoreClient> client(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_clients.find(h);
  return it == g_clients.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t ptpu_store_server_start(int port) {
  auto s = std::make_shared<StoreServer>();
  if (!s->start(port)) return -1;
  std::lock_guard<std::mutex> l(g_mu);
  int64_t id = g_next++;
  g_servers[id] = s;
  return id;
}

int ptpu_store_server_port(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_servers.find(h);
  return it == g_servers.end() ? -1 : it->second->port;
}

void ptpu_store_server_stop(int64_t h) {
  std::shared_ptr<StoreServer> s;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_servers.find(h);
    if (it == g_servers.end()) return;
    s = it->second;
    g_servers.erase(it);
  }
  s->stop();
}

int64_t ptpu_store_client_create(const char* host, int port, double timeout_s) {
  auto c = std::make_shared<StoreClient>();
  if (!c->connect_to(host, port, timeout_s)) return -1;
  std::lock_guard<std::mutex> l(g_mu);
  int64_t id = g_next++;
  g_clients[id] = c;
  return id;
}

void ptpu_store_client_destroy(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_clients.find(h);
  if (it == g_clients.end()) return;
  if (it->second->fd >= 0) ::close(it->second->fd);
  g_clients.erase(it);
}

int ptpu_store_set(int64_t h, const char* key, const uint8_t* val,
                   int64_t len) {
  auto c = client(h);
  if (!c) return PTPU_ERR;
  return c->request(1, key, (uint64_t)len, val, len, nullptr) == 0 ? PTPU_OK
                                                                   : PTPU_ERR;
}

int64_t ptpu_store_get(int64_t h, const char* key, uint8_t* buf,
                       int64_t buflen, double timeout_s) {
  auto c = client(h);
  if (!c) return -2;
  uint64_t ms = timeout_s < 0 ? 0 : (uint64_t)(timeout_s * 1000);
  std::string out;
  int64_t status = c->request(2, key, ms, nullptr, 0, &out);
  if (status < 0) return status;
  int64_t n = std::min<int64_t>(status, buflen);
  if (n > 0) memcpy(buf, out.data(), n);
  return status;
}

int64_t ptpu_store_add(int64_t h, const char* key, int64_t delta) {
  auto c = client(h);
  if (!c) return INT64_MIN;
  return c->request(3, key, (uint64_t)delta, nullptr, 0, nullptr);
}

int ptpu_store_wait(int64_t h, const char* key, double timeout_s) {
  auto c = client(h);
  if (!c) return PTPU_ERR;
  uint64_t ms = timeout_s < 0 ? 0 : (uint64_t)(timeout_s * 1000);
  return c->request(4, key, ms, nullptr, 0, nullptr) == 0 ? PTPU_OK
                                                          : PTPU_TIMEOUT;
}

}  // extern "C"
