// Bounded MPMC blocking queue with close semantics.
//
// Serves the DataLoader prefetch pipeline the way the reference's
// LoDTensorBlockingQueue (paddle/fluid/operators/reader/
// lod_tensor_blocking_queue.h:30, blocking_queue.h:28) feeds its buffered
// reader: producers block when full, consumers block when empty, and
// close() wakes everyone so shutdown never deadlocks. Payloads are opaque
// uint64 tokens — the Python side maps tokens to batch objects, so the
// queue itself never touches the GIL (ctypes releases it around calls,
// letting waits overlap with Python-side work).

#include "ptpu_runtime.h"

#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <unordered_map>

namespace {

struct BlockingQueue {
  explicit BlockingQueue(int64_t cap) : capacity(cap) {}
  int64_t capacity;
  std::deque<uint64_t> items;
  bool closed = false;
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
};

std::mutex g_reg_mu;
std::unordered_map<int64_t, std::shared_ptr<BlockingQueue>> g_queues;
int64_t g_next_id = 1;

std::shared_ptr<BlockingQueue> get(int64_t h) {
  std::lock_guard<std::mutex> l(g_reg_mu);
  auto it = g_queues.find(h);
  return it == g_queues.end() ? nullptr : it->second;
}

bool wait_on(std::condition_variable& cv, std::unique_lock<std::mutex>& l,
             double timeout_s, const std::function<bool()>& pred) {
  if (timeout_s < 0) {
    cv.wait(l, pred);
    return true;
  }
  return cv.wait_for(l, std::chrono::duration<double>(timeout_s), pred);
}

}  // namespace

extern "C" {

uint64_t ptpu_now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t ptpu_bq_create(int64_t capacity) {
  if (capacity <= 0) capacity = 1;
  std::lock_guard<std::mutex> l(g_reg_mu);
  int64_t id = g_next_id++;
  g_queues[id] = std::make_shared<BlockingQueue>(capacity);
  return id;
}

int ptpu_bq_push(int64_t h, uint64_t value, double timeout_s) {
  auto q = get(h);
  if (!q) return PTPU_ERR;
  std::unique_lock<std::mutex> l(q->mu);
  bool ok = wait_on(q->not_full, l, timeout_s, [&] {
    return q->closed || (int64_t)q->items.size() < q->capacity;
  });
  if (!ok) return PTPU_TIMEOUT;
  if (q->closed) return PTPU_CLOSED;
  q->items.push_back(value);
  q->not_empty.notify_one();
  return PTPU_OK;
}

int ptpu_bq_pop(int64_t h, uint64_t* out, double timeout_s) {
  auto q = get(h);
  if (!q) return PTPU_ERR;
  std::unique_lock<std::mutex> l(q->mu);
  bool ok = wait_on(q->not_empty, l, timeout_s,
                    [&] { return q->closed || !q->items.empty(); });
  if (!ok) return PTPU_TIMEOUT;
  if (q->items.empty()) return PTPU_CLOSED;  // closed and drained
  *out = q->items.front();
  q->items.pop_front();
  q->not_full.notify_one();
  return PTPU_OK;
}

int64_t ptpu_bq_size(int64_t h) {
  auto q = get(h);
  if (!q) return -1;
  std::lock_guard<std::mutex> l(q->mu);
  return (int64_t)q->items.size();
}

int64_t ptpu_bq_capacity(int64_t h) {
  auto q = get(h);
  return q ? q->capacity : -1;
}

void ptpu_bq_close(int64_t h) {
  auto q = get(h);
  if (!q) return;
  std::lock_guard<std::mutex> l(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

int ptpu_bq_is_closed(int64_t h) {
  auto q = get(h);
  if (!q) return 1;
  std::lock_guard<std::mutex> l(q->mu);
  return q->closed ? 1 : 0;
}

void ptpu_bq_destroy(int64_t h) {
  ptpu_bq_close(h);
  std::lock_guard<std::mutex> l(g_reg_mu);
  g_queues.erase(h);
}

}  // extern "C"
