// C API of the paddle_tpu native host runtime.
//
// TPU-native analogue of the reference's C++ runtime services:
//   blocking queue  <- paddle/fluid/operators/reader/lod_tensor_blocking_queue.h:30
//   TCP store       <- paddle/phi/core/distributed/store/tcp_store.h:120
//   host tracer     <- paddle/fluid/platform/profiler/host_event_recorder.h
//   stat registry   <- paddle/fluid/memory/stats.h
//   work queue      <- paddle/fluid/framework/new_executor/workqueue/nonblocking_threadpool.h
//
// Everything is exposed as a flat extern "C" surface so Python binds via
// ctypes (no pybind11 in this image). Handles are opaque int64 ids.

#ifndef PTPU_RUNTIME_H_
#define PTPU_RUNTIME_H_

#include <stdint.h>
#include <stddef.h>

#if defined(__cplusplus)
extern "C" {
#endif

#define PTPU_OK 0
#define PTPU_TIMEOUT 1
#define PTPU_CLOSED 2
#define PTPU_ERR 3

// ---- clock ----
uint64_t ptpu_now_ns();

// ---- blocking queue (bounded MPMC, uint64 payload tokens) ----
int64_t ptpu_bq_create(int64_t capacity);
int ptpu_bq_push(int64_t h, uint64_t value, double timeout_s);
int ptpu_bq_pop(int64_t h, uint64_t* out, double timeout_s);
int64_t ptpu_bq_size(int64_t h);
int64_t ptpu_bq_capacity(int64_t h);
void ptpu_bq_close(int64_t h);   // wake all waiters; pops drain, pushes fail
int ptpu_bq_is_closed(int64_t h);
void ptpu_bq_destroy(int64_t h);

// ---- TCP store (KV rendezvous) ----
// Server: start/stop a listener owning the map. Client: connect to one.
// get() blocks server-side until the key exists (or timeout).
int64_t ptpu_store_server_start(int port);          // handle or -1
int ptpu_store_server_port(int64_t h);
void ptpu_store_server_stop(int64_t h);
int64_t ptpu_store_client_create(const char* host, int port, double timeout_s);
void ptpu_store_client_destroy(int64_t h);
int ptpu_store_set(int64_t h, const char* key, const uint8_t* val, int64_t len);
// returns value length (copied into buf up to buflen), -1 timeout, -2 error
int64_t ptpu_store_get(int64_t h, const char* key, uint8_t* buf,
                       int64_t buflen, double timeout_s);
int64_t ptpu_store_add(int64_t h, const char* key, int64_t delta);  // new value
int ptpu_store_wait(int64_t h, const char* key, double timeout_s);

// ---- host tracer ----
void ptpu_trace_enable();
void ptpu_trace_disable();
int ptpu_trace_is_enabled();
void ptpu_trace_begin(const char* name);   // push TLS range
void ptpu_trace_end();                     // pop TLS range -> event
void ptpu_trace_instant(const char* name);
void ptpu_trace_counter(const char* name, int64_t value);
int64_t ptpu_trace_count();
void ptpu_trace_clear();
// Export all recorded events as a chrome://tracing JSON file.
int ptpu_trace_export(const char* path);
// Copy a compact binary dump (for Python-side summaries):
// repeated records {u8 kind; u64 t0; u64 t1; i64 tid; i64 value; u32 namelen; name}
int64_t ptpu_trace_dump(uint8_t* buf, int64_t buflen);

// ---- stat registry ----
void ptpu_stat_update(const char* name, int64_t delta);
int64_t ptpu_stat_current(const char* name);
int64_t ptpu_stat_peak(const char* name);
void ptpu_stat_reset(const char* name);
// newline-joined names; returns needed length
int64_t ptpu_stat_names(char* buf, int64_t buflen);

// ---- work queue (thread pool) ----
typedef void (*ptpu_task_fn)(void* arg);
int64_t ptpu_wq_create(int num_threads);
int ptpu_wq_submit(int64_t h, ptpu_task_fn fn, void* arg);
void ptpu_wq_wait_idle(int64_t h);
int64_t ptpu_wq_pending(int64_t h);
void ptpu_wq_destroy(int64_t h);

// ---- parameter server ----
// TPU-native analogue of the reference brpc PS
// (paddle/fluid/distributed/ps/: brpc_ps_server.h, memory_dense_table.h,
// memory_sparse_table.h, sparse_sgd_rule.h): dense + sparse (hash) float
// tables behind a threaded TCP server; server-side SGD apply on push
// (the accessor rule), create-on-first-pull sparse rows with uniform init.
int64_t ptpu_ps_server_start(int port);             // handle or -1
int ptpu_ps_server_port(int64_t h);
void ptpu_ps_server_stop(int64_t h);
int64_t ptpu_ps_client_create(const char* host, int port, double timeout_s);
void ptpu_ps_client_destroy(int64_t h);
int ptpu_ps_create_dense(int64_t c, int32_t table, int64_t dim);
// rule: 0=naive SGD, 1=adagrad per-feature (eps).  max_mem_rows>0 caps
// in-memory rows with LRU spill to `spill_path` (the SSD sparse table).
int ptpu_ps_create_sparse(int64_t c, int32_t table, int64_t dim,
                          double init_scale, uint64_t seed, uint8_t rule,
                          double eps, uint64_t max_mem_rows,
                          const char* spill_path);
int ptpu_ps_pull_dense(int64_t c, int32_t table, float* out, int64_t dim);
int ptpu_ps_set_dense(int64_t c, int32_t table, const float* val,
                      int64_t dim);
// server applies w -= lr * grad
int ptpu_ps_push_dense(int64_t c, int32_t table, const float* grad,
                       int64_t dim, double lr);
int ptpu_ps_pull_sparse(int64_t c, int32_t table, const uint64_t* keys,
                        int64_t n, int64_t dim, float* out /* n*dim */);
int ptpu_ps_push_sparse(int64_t c, int32_t table, const uint64_t* keys,
                        int64_t n, int64_t dim, const float* grads,
                        double lr);
int64_t ptpu_ps_sparse_size(int64_t c, int32_t table);  // #keys (total)
int64_t ptpu_ps_sparse_mem_rows(int64_t c, int32_t table);  // in-memory
// Graph tables (reference common_graph_table.h:501): adjacency lists
// served with with-replacement neighbor sampling (isolated nodes echo
// themselves) and degree queries.
int ptpu_ps_create_graph(int64_t c, int32_t table, uint64_t seed);
int ptpu_ps_graph_add_edges(int64_t c, int32_t table, const uint64_t* src,
                            const uint64_t* dst, int64_t n);
int ptpu_ps_graph_sample(int64_t c, int32_t table, const uint64_t* nodes,
                         int64_t n, int64_t k, uint64_t* out /* n*k */);
int ptpu_ps_graph_degree(int64_t c, int32_t table, const uint64_t* nodes,
                         int64_t n, uint64_t* out /* n */);

#if defined(__cplusplus)
}  // extern "C"
#endif

#endif  // PTPU_RUNTIME_H_
