// Work queue: fixed thread pool draining a task queue, with idle barrier.
//
// TPU-native analogue of the reference executor's async work queue
// (paddle/fluid/framework/new_executor/workqueue/nonblocking_threadpool.h
// used by ProgramInterpreter::RunInstructionAsync): host-side tasks —
// dataloader fetches, checkpoint shard writes, callback fan-out — are
// submitted as C function pointers (ctypes callbacks acquire the GIL
// themselves when the task is Python).

#include "ptpu_runtime.h"

#include <condition_variable>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct WorkQueue {
  std::vector<std::thread> threads;
  std::deque<std::pair<ptpu_task_fn, void*>> tasks;
  std::mutex mu;
  std::condition_variable cv;       // workers wait for tasks
  std::condition_variable idle_cv;  // waiters for all-done
  int64_t in_flight = 0;
  bool stopping = false;

  explicit WorkQueue(int n) {
    for (int i = 0; i < n; ++i) {
      threads.emplace_back([this] { loop(); });
    }
  }

  void loop() {
    for (;;) {
      std::pair<ptpu_task_fn, void*> task;
      {
        std::unique_lock<std::mutex> l(mu);
        cv.wait(l, [&] { return stopping || !tasks.empty(); });
        if (stopping && tasks.empty()) return;
        task = tasks.front();
        tasks.pop_front();
        ++in_flight;
      }
      task.first(task.second);
      {
        std::lock_guard<std::mutex> l(mu);
        --in_flight;
        if (tasks.empty() && in_flight == 0) idle_cv.notify_all();
      }
    }
  }

  void stop() {
    {
      std::lock_guard<std::mutex> l(mu);
      stopping = true;
    }
    cv.notify_all();
    for (auto& t : threads)
      if (t.joinable()) t.join();
  }
};

std::mutex g_mu;
std::unordered_map<int64_t, std::shared_ptr<WorkQueue>> g_queues;
int64_t g_next = 1;

std::shared_ptr<WorkQueue> get(int64_t h) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_queues.find(h);
  return it == g_queues.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int64_t ptpu_wq_create(int num_threads) {
  if (num_threads <= 0) num_threads = 1;
  std::lock_guard<std::mutex> l(g_mu);
  int64_t id = g_next++;
  g_queues[id] = std::make_shared<WorkQueue>(num_threads);
  return id;
}

int ptpu_wq_submit(int64_t h, ptpu_task_fn fn, void* arg) {
  auto q = get(h);
  if (!q) return PTPU_ERR;
  {
    std::lock_guard<std::mutex> l(q->mu);
    if (q->stopping) return PTPU_CLOSED;
    q->tasks.emplace_back(fn, arg);
  }
  q->cv.notify_one();
  return PTPU_OK;
}

void ptpu_wq_wait_idle(int64_t h) {
  auto q = get(h);
  if (!q) return;
  std::unique_lock<std::mutex> l(q->mu);
  q->idle_cv.wait(l, [&] { return q->tasks.empty() && q->in_flight == 0; });
}

int64_t ptpu_wq_pending(int64_t h) {
  auto q = get(h);
  if (!q) return -1;
  std::lock_guard<std::mutex> l(q->mu);
  return (int64_t)q->tasks.size() + q->in_flight;
}

void ptpu_wq_destroy(int64_t h) {
  std::shared_ptr<WorkQueue> q;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_queues.find(h);
    if (it == g_queues.end()) return;
    q = it->second;
    g_queues.erase(it);
  }
  q->stop();
}

}  // extern "C"
