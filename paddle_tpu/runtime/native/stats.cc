// Named stat registry: current + peak counters with atomic updates.
//
// TPU-native analogue of the reference's memory stat system
// (paddle/fluid/memory/stats.h — DeviceMemoryStatCurrentValue /
// HostMemoryStatUpdate): framework subsystems bump named counters
// ("host_queue_bytes", "pinned_pool_bytes", ...) and Python reads them
// via paddle_tpu.device.stats. Device HBM numbers come from
// jax's memory_stats(); this covers the host runtime side.

#include "ptpu_runtime.h"

#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {

struct Stat {
  int64_t current = 0;
  int64_t peak = 0;
};

std::mutex g_mu;
std::map<std::string, Stat> g_stats;

}  // namespace

extern "C" {

void ptpu_stat_update(const char* name, int64_t delta) {
  std::lock_guard<std::mutex> l(g_mu);
  Stat& s = g_stats[name];
  s.current += delta;
  if (s.current > s.peak) s.peak = s.current;
}

int64_t ptpu_stat_current(const char* name) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.current;
}

int64_t ptpu_stat_peak(const char* name) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.peak;
}

void ptpu_stat_reset(const char* name) {
  std::lock_guard<std::mutex> l(g_mu);
  g_stats.erase(name);
}

int64_t ptpu_stat_names(char* buf, int64_t buflen) {
  std::lock_guard<std::mutex> l(g_mu);
  std::string joined;
  for (const auto& kv : g_stats) {
    if (!joined.empty()) joined.push_back('\n');
    joined += kv.first;
  }
  if (buf && (int64_t)joined.size() < buflen) {
    memcpy(buf, joined.c_str(), joined.size() + 1);
  }
  return (int64_t)joined.size();
}

}  // extern "C"
