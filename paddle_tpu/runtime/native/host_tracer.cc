// Host event tracer: per-thread event buffers + chrome-trace export.
//
// TPU-native analogue of the reference's host profiling layer
// (paddle/fluid/platform/profiler/host_tracer.cc + host_event_recorder.h:
// TLS ring buffers of RecordEvent ranges merged at export;
// chrometracing_logger.cc writes the chrome://tracing JSON). Device-side
// events come from XLA's own profiler; this records the host side
// (dataloader, dispatch, python ranges) with nanosecond steady-clock
// timestamps and near-zero overhead when disabled (one relaxed atomic
// load on the hot path).

#include "ptpu_runtime.h"

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

enum Kind : uint8_t { kRange = 0, kInstant = 1, kCounter = 2 };

struct Event {
  Kind kind;
  uint64_t t0;
  uint64_t t1;
  int64_t value;
  std::string name;
};

struct ThreadBuffer {
  int64_t tid;
  std::vector<Event> events;
  std::vector<std::pair<std::string, uint64_t>> open;  // begin() stack
  std::mutex mu;  // export/clear vs. owning thread
};

struct RetiredEvent {
  int64_t tid;
  Event event;
};

std::atomic<bool> g_enabled{false};
std::mutex g_bufs_mu;
std::vector<ThreadBuffer*> g_bufs;
std::vector<RetiredEvent> g_retired;  // events of exited threads
std::atomic<int64_t> g_next_tid{1};

// TLS holder: on thread exit, move the buffer's events into g_retired and
// free it, so short-lived worker threads (dataloader pools are re-created
// per epoch) don't grow g_bufs without bound while their profile data
// still survives until export/clear.
struct TlsHolder {
  ThreadBuffer* buf;
  explicit TlsHolder() {
    buf = new ThreadBuffer();
    buf->tid = g_next_tid.fetch_add(1);
    std::lock_guard<std::mutex> l(g_bufs_mu);
    g_bufs.push_back(buf);
  }
  ~TlsHolder() {
    std::lock_guard<std::mutex> l(g_bufs_mu);
    for (auto& e : buf->events) g_retired.push_back({buf->tid, std::move(e)});
    g_bufs.erase(std::remove(g_bufs.begin(), g_bufs.end(), buf), g_bufs.end());
    delete buf;
  }
};

ThreadBuffer* tls_buffer() {
  thread_local TlsHolder holder;
  return holder.buf;
}

void json_escape(std::string* out, const std::string& s) {
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out->push_back('\\');
      out->push_back(c);
    } else if ((unsigned char)c < 0x20) {
      char tmp[8];
      snprintf(tmp, sizeof(tmp), "\\u%04x", c);
      *out += tmp;
    } else {
      out->push_back(c);
    }
  }
}

}  // namespace

extern "C" {

void ptpu_trace_enable() { g_enabled.store(true); }
void ptpu_trace_disable() { g_enabled.store(false); }
int ptpu_trace_is_enabled() { return g_enabled.load() ? 1 : 0; }

void ptpu_trace_begin(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls_buffer();
  std::lock_guard<std::mutex> l(b->mu);
  b->open.emplace_back(name, ptpu_now_ns());
}

void ptpu_trace_end() {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls_buffer();
  std::lock_guard<std::mutex> l(b->mu);
  if (b->open.empty()) return;
  auto [name, t0] = b->open.back();
  b->open.pop_back();
  b->events.push_back({kRange, t0, ptpu_now_ns(), 0, std::move(name)});
}

void ptpu_trace_instant(const char* name) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls_buffer();
  std::lock_guard<std::mutex> l(b->mu);
  uint64_t t = ptpu_now_ns();
  b->events.push_back({kInstant, t, t, 0, name});
}

void ptpu_trace_counter(const char* name, int64_t value) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  auto* b = tls_buffer();
  std::lock_guard<std::mutex> l(b->mu);
  uint64_t t = ptpu_now_ns();
  b->events.push_back({kCounter, t, t, value, name});
}

int64_t ptpu_trace_count() {
  std::lock_guard<std::mutex> lr(g_bufs_mu);
  int64_t n = (int64_t)g_retired.size();
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> l(b->mu);
    n += (int64_t)b->events.size();
  }
  return n;
}

void ptpu_trace_clear() {
  std::lock_guard<std::mutex> lr(g_bufs_mu);
  g_retired.clear();
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> l(b->mu);
    b->events.clear();
    b->open.clear();
  }
}

void write_event_json(FILE* f, bool* first, int64_t tid, const Event& e) {
  std::string name;
  json_escape(&name, e.name);
  double us0 = e.t0 / 1000.0;
  if (!*first) fputs(",\n", f);
  *first = false;
  if (e.kind == kRange) {
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%lld,"
            "\"ts\":%.3f,\"dur\":%.3f}",
            name.c_str(), (long long)tid, us0, (e.t1 - e.t0) / 1000.0);
  } else if (e.kind == kInstant) {
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"i\",\"pid\":0,\"tid\":%lld,"
            "\"ts\":%.3f,\"s\":\"t\"}",
            name.c_str(), (long long)tid, us0);
  } else {
    fprintf(f,
            "{\"name\":\"%s\",\"ph\":\"C\",\"pid\":0,\"tid\":%lld,"
            "\"ts\":%.3f,\"args\":{\"value\":%lld}}",
            name.c_str(), (long long)tid, us0, (long long)e.value);
  }
}

int ptpu_trace_export(const char* path) {
  FILE* f = fopen(path, "w");
  if (!f) return PTPU_ERR;
  fputs("{\"traceEvents\":[\n", f);
  bool first = true;
  std::lock_guard<std::mutex> lr(g_bufs_mu);
  for (const auto& r : g_retired) write_event_json(f, &first, r.tid, r.event);
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> l(b->mu);
    for (const auto& e : b->events) write_event_json(f, &first, b->tid, e);
  }
  fputs("\n]}\n", f);
  fclose(f);
  return PTPU_OK;
}

namespace {
// Appends one record if it fits. Returns false (and leaves *off untouched)
// when the buffer is exhausted, so a partial dump never contains a torn or
// phantom record — the return value of ptpu_trace_dump is exactly the
// number of valid bytes written (or needed, when buf is null).
bool dump_one(uint8_t* buf, int64_t buflen, int64_t* off, int64_t tid,
              const Event& e) {
  uint32_t namelen = (uint32_t)e.name.size();
  int64_t rec = 1 + 8 + 8 + 8 + 8 + 4 + namelen;
  if (buf) {
    if (*off + rec > buflen) return false;
    uint8_t* p = buf + *off;
    *p++ = (uint8_t)e.kind;
    memcpy(p, &e.t0, 8); p += 8;
    memcpy(p, &e.t1, 8); p += 8;
    memcpy(p, &tid, 8); p += 8;
    memcpy(p, &e.value, 8); p += 8;
    memcpy(p, &namelen, 4); p += 4;
    memcpy(p, e.name.data(), namelen);
  }
  *off += rec;
  return true;
}
}  // namespace

int64_t ptpu_trace_dump(uint8_t* buf, int64_t buflen) {
  int64_t off = 0;
  std::lock_guard<std::mutex> lr(g_bufs_mu);
  for (const auto& r : g_retired) {
    if (!dump_one(buf, buflen, &off, r.tid, r.event)) return off;
  }
  for (auto* b : g_bufs) {
    std::lock_guard<std::mutex> l(b->mu);
    for (const auto& e : b->events) {
      if (!dump_one(buf, buflen, &off, b->tid, e)) return off;
    }
  }
  return off;
}

}  // extern "C"
