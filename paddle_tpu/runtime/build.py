"""On-demand build of the native runtime shared library.

Compiles ``paddle_tpu/runtime/native/*.cc`` into a cached ``.so`` with g++
(the image has no pybind11; bindings are ctypes over the extern "C" surface
declared in ``ptpu_runtime.h``). The build is keyed by a hash of the sources
so edits trigger exactly one rebuild; concurrent builders (pytest-xdist,
multi-process launch) race benignly via an atomic rename.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "native")
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "_cache")


def _sources():
    return sorted(
        os.path.join(_NATIVE_DIR, f)
        for f in os.listdir(_NATIVE_DIR)
        if f.endswith(".cc")
    )


def _source_hash() -> str:
    h = hashlib.sha256()
    for path in _sources() + [os.path.join(_NATIVE_DIR, "ptpu_runtime.h")]:
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build_native(verbose: bool = False) -> str:
    """Return the path to the built shared library, compiling if needed."""
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"libptpu_runtime_{_source_hash()}.so")
    if os.path.exists(so_path):
        return so_path
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_CACHE_DIR)
    os.close(fd)
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
        "-Wall", f"-I{_NATIVE_DIR}", *_sources(), "-o", tmp,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native runtime build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, so_path)  # atomic: concurrent builds converge
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if verbose:
        print(f"[paddle_tpu] built native runtime -> {so_path}")
    return so_path
