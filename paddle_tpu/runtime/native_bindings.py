"""ctypes bindings over the native host runtime (see native/ptpu_runtime.h).

Exposes Pythonic wrappers:

- :class:`BlockingQueue` — bounded MPMC queue of Python objects; blocking
  semantics live in C++ (≙ LoDTensorBlockingQueue), object identity is kept
  on the Python side via a token table.
- :class:`TCPStoreServer` / :class:`TCPStore` — KV rendezvous
  (≙ phi TCPStore) for multi-process bootstrap and barriers.
- :class:`HostTracer` — process-wide host event recorder with
  chrome-trace export (≙ host_event_recorder + chrometracing_logger).
- :func:`stat_update` etc. — named current/peak counters (≙ memory/stats.h).
- :class:`WorkQueue` — C++ thread pool running Python callables
  (≙ nonblocking_threadpool).

If the toolchain is unavailable the import raises and callers fall back to
pure-Python shims (see paddle_tpu.runtime.__init__).
"""

from __future__ import annotations

import ctypes
import itertools
import struct
import threading
from typing import Any, Optional

from .build import build_native

_lib = ctypes.CDLL(build_native())

_i64, _u64, _i32 = ctypes.c_int64, ctypes.c_uint64, ctypes.c_int
_dbl, _chp, _u8p = ctypes.c_double, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint8)

_lib.ptpu_now_ns.restype = _u64
_lib.ptpu_bq_create.restype = _i64
_lib.ptpu_bq_create.argtypes = [_i64]
_lib.ptpu_bq_push.restype = _i32
_lib.ptpu_bq_push.argtypes = [_i64, _u64, _dbl]
_lib.ptpu_bq_pop.restype = _i32
_lib.ptpu_bq_pop.argtypes = [_i64, ctypes.POINTER(_u64), _dbl]
_lib.ptpu_bq_size.restype = _i64
_lib.ptpu_bq_size.argtypes = [_i64]
_lib.ptpu_bq_capacity.restype = _i64
_lib.ptpu_bq_capacity.argtypes = [_i64]
_lib.ptpu_bq_close.argtypes = [_i64]
_lib.ptpu_bq_is_closed.restype = _i32
_lib.ptpu_bq_is_closed.argtypes = [_i64]
_lib.ptpu_bq_destroy.argtypes = [_i64]

_lib.ptpu_store_server_start.restype = _i64
_lib.ptpu_store_server_start.argtypes = [_i32]
_lib.ptpu_store_server_port.restype = _i32
_lib.ptpu_store_server_port.argtypes = [_i64]
_lib.ptpu_store_server_stop.argtypes = [_i64]
_lib.ptpu_store_client_create.restype = _i64
_lib.ptpu_store_client_create.argtypes = [_chp, _i32, _dbl]
_lib.ptpu_store_client_destroy.argtypes = [_i64]
_lib.ptpu_store_set.restype = _i32
_lib.ptpu_store_set.argtypes = [_i64, _chp, _u8p, _i64]
_lib.ptpu_store_get.restype = _i64
_lib.ptpu_store_get.argtypes = [_i64, _chp, _u8p, _i64, _dbl]
_lib.ptpu_store_add.restype = _i64
_lib.ptpu_store_add.argtypes = [_i64, _chp, _i64]
_lib.ptpu_store_wait.restype = _i32
_lib.ptpu_store_wait.argtypes = [_i64, _chp, _dbl]

_lib.ptpu_trace_begin.argtypes = [_chp]
_lib.ptpu_trace_instant.argtypes = [_chp]
_lib.ptpu_trace_counter.argtypes = [_chp, _i64]
_lib.ptpu_trace_count.restype = _i64
_lib.ptpu_trace_export.restype = _i32
_lib.ptpu_trace_export.argtypes = [_chp]
_lib.ptpu_trace_dump.restype = _i64
_lib.ptpu_trace_dump.argtypes = [_u8p, _i64]
_lib.ptpu_trace_is_enabled.restype = _i32

_lib.ptpu_stat_update.argtypes = [_chp, _i64]
_lib.ptpu_stat_current.restype = _i64
_lib.ptpu_stat_current.argtypes = [_chp]
_lib.ptpu_stat_peak.restype = _i64
_lib.ptpu_stat_peak.argtypes = [_chp]
_lib.ptpu_stat_reset.argtypes = [_chp]
_lib.ptpu_stat_names.restype = _i64
_lib.ptpu_stat_names.argtypes = [_chp, _i64]

_TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_lib.ptpu_wq_create.restype = _i64
_lib.ptpu_wq_create.argtypes = [_i32]
_lib.ptpu_wq_submit.restype = _i32
_lib.ptpu_wq_submit.argtypes = [_i64, _TASK_FN, ctypes.c_void_p]
_lib.ptpu_wq_wait_idle.argtypes = [_i64]
_lib.ptpu_wq_pending.restype = _i64
_lib.ptpu_wq_pending.argtypes = [_i64]
_lib.ptpu_wq_destroy.argtypes = [_i64]

OK, TIMEOUT, CLOSED = 0, 1, 2


def now_ns() -> int:
    return int(_lib.ptpu_now_ns())


class QueueClosed(Exception):
    pass


class BlockingQueue:
    """Bounded blocking queue of arbitrary Python objects.

    C++ owns the bounded/blocking/close semantics; Python keeps a token→
    object table so payloads never cross the ABI.
    """

    def __init__(self, capacity: int):
        self._h = _lib.ptpu_bq_create(capacity)
        self._tokens = itertools.count(1)
        self._objs: dict[int, Any] = {}
        self._mu = threading.Lock()

    def push(self, obj: Any, timeout: Optional[float] = None) -> bool:
        tok = next(self._tokens)
        with self._mu:
            self._objs[tok] = obj
        rc = _lib.ptpu_bq_push(self._h, tok, -1.0 if timeout is None else timeout)
        if rc != OK:
            with self._mu:
                self._objs.pop(tok, None)
            if rc == CLOSED:
                raise QueueClosed("queue closed")
            return False  # timeout
        return True

    def pop(self, timeout: Optional[float] = None) -> Any:
        out = _u64(0)
        rc = _lib.ptpu_bq_pop(self._h, ctypes.byref(out),
                              -1.0 if timeout is None else timeout)
        if rc == CLOSED:
            raise QueueClosed("queue closed and drained")
        if rc != OK:
            raise TimeoutError("BlockingQueue.pop timed out")
        with self._mu:
            return self._objs.pop(int(out.value))

    def size(self) -> int:
        return int(_lib.ptpu_bq_size(self._h))

    def capacity(self) -> int:
        return int(_lib.ptpu_bq_capacity(self._h))

    def close(self):
        _lib.ptpu_bq_close(self._h)

    @property
    def closed(self) -> bool:
        return bool(_lib.ptpu_bq_is_closed(self._h))

    def __del__(self):
        try:
            _lib.ptpu_bq_destroy(self._h)
        except Exception:
            pass


class TCPStoreServer:
    """Master side of the rendezvous store (run on rank 0's host)."""

    def __init__(self, port: int = 0):
        self._h = _lib.ptpu_store_server_start(port)
        if self._h < 0:
            raise OSError(f"TCPStoreServer: cannot bind port {port}")

    @property
    def port(self) -> int:
        return int(_lib.ptpu_store_server_port(self._h))

    def stop(self):
        if self._h >= 0:
            _lib.ptpu_store_server_stop(self._h)
            self._h = -1

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class TCPStore:
    """Client handle; mirrors the reference TCPStore API
    (set/get/add/wait — paddle/phi/core/distributed/store/tcp_store.h:120)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self._h = _lib.ptpu_store_client_create(host.encode(), port, timeout)
        if self._h < 0:
            raise ConnectionError(f"TCPStore: cannot connect {host}:{port}")

    def set(self, key: str, value: bytes):
        buf = (ctypes.c_uint8 * max(len(value), 1)).from_buffer_copy(
            value or b"\0")
        rc = _lib.ptpu_store_set(self._h, key.encode(), buf, len(value))
        if rc != OK:
            raise IOError("TCPStore.set failed")

    def get(self, key: str, timeout: float = 60.0) -> bytes:
        size = 1 << 16
        while True:
            buf = (ctypes.c_uint8 * size)()
            n = _lib.ptpu_store_get(self._h, key.encode(), buf, size, timeout)
            if n == -1:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
            if n < 0:
                raise IOError("TCPStore.get failed")
            if n <= size:
                return bytes(buf[: int(n)])
            size = int(n)  # retry with exact size

    def add(self, key: str, delta: int = 1) -> int:
        v = _lib.ptpu_store_add(self._h, key.encode(), delta)
        if v == -(2**63):
            raise IOError("TCPStore.add failed")
        return int(v)

    def wait(self, key: str, timeout: float = 60.0):
        rc = _lib.ptpu_store_wait(self._h, key.encode(), timeout)
        if rc != OK:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def close(self):
        if self._h >= 0:
            _lib.ptpu_store_client_destroy(self._h)
            self._h = -1

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class HostTracer:
    """Process-wide host tracer (all methods are static; state is in C++).

    ``enabled`` mirrors the C++ flag as a plain Python attribute so hot
    paths (op dispatch) can check it without crossing the ABI.
    """

    enabled = False

    @staticmethod
    def enable():
        HostTracer.enabled = True
        _lib.ptpu_trace_enable()

    @staticmethod
    def disable():
        HostTracer.enabled = False
        _lib.ptpu_trace_disable()

    @staticmethod
    def is_enabled() -> bool:
        return bool(_lib.ptpu_trace_is_enabled())

    @staticmethod
    def begin(name: str):
        _lib.ptpu_trace_begin(name.encode())

    @staticmethod
    def end():
        _lib.ptpu_trace_end()

    @staticmethod
    def instant(name: str):
        _lib.ptpu_trace_instant(name.encode())

    @staticmethod
    def counter(name: str, value: int):
        _lib.ptpu_trace_counter(name.encode(), value)

    @staticmethod
    def count() -> int:
        return int(_lib.ptpu_trace_count())

    @staticmethod
    def clear():
        _lib.ptpu_trace_clear()

    @staticmethod
    def export_chrome_trace(path: str):
        if _lib.ptpu_trace_export(path.encode()) != OK:
            raise IOError(f"cannot write trace to {path}")

    @staticmethod
    def events() -> list:
        """Decode the binary dump into [(kind, t0_ns, t1_ns, tid, value, name)]."""
        need = _lib.ptpu_trace_dump(None, 0)
        if need <= 0:
            return []
        # slack absorbs events recorded between the size query and the dump;
        # dump never writes a partial record, so raw[:got] is always valid
        size = int(need) + 65536
        buf = (ctypes.c_uint8 * size)()
        got = _lib.ptpu_trace_dump(buf, size)
        raw = bytes(buf[: int(got)])
        out, off = [], 0
        while off + 37 <= len(raw):
            kind = raw[off]
            t0, t1, tid, value, namelen = struct.unpack_from("<QQqqI", raw, off + 1)
            off += 37
            name = raw[off: off + namelen].decode("utf-8", "replace")
            off += namelen
            out.append((kind, t0, t1, tid, value, name))
        return out


def stat_update(name: str, delta: int):
    _lib.ptpu_stat_update(name.encode(), delta)


def stat_current(name: str) -> int:
    return int(_lib.ptpu_stat_current(name.encode()))


def stat_peak(name: str) -> int:
    return int(_lib.ptpu_stat_peak(name.encode()))


def stat_reset(name: str):
    _lib.ptpu_stat_reset(name.encode())


def stat_names() -> list:
    n = _lib.ptpu_stat_names(None, 0)
    if n <= 0:
        return []
    buf = ctypes.create_string_buffer(int(n) + 1)
    _lib.ptpu_stat_names(buf, int(n) + 1)
    return buf.value.decode().split("\n") if buf.value else []


class WorkQueue:
    """C++ thread pool executing Python callables.

    ctypes CFUNCTYPE trampolines acquire the GIL per task, so pure-numpy
    tasks overlap (numpy releases the GIL) while scheduling/wakeups stay
    native.
    """

    def __init__(self, num_threads: int):
        self._h = _lib.ptpu_wq_create(num_threads)
        self._mu = threading.Lock()
        self._tasks: dict[int, Any] = {}
        self._ids = itertools.count(1)
        self._errors: list = []

        def trampoline(arg):
            tid = int(arg)
            with self._mu:
                fn = self._tasks.pop(tid)
            try:
                fn()
            except Exception as e:  # surfaced on wait_idle
                with self._mu:
                    self._errors.append(e)

        self._cb = _TASK_FN(trampoline)  # keep alive

    def submit(self, fn):
        tid = next(self._ids)
        with self._mu:
            self._tasks[tid] = fn
        rc = _lib.ptpu_wq_submit(self._h, self._cb, ctypes.c_void_p(tid))
        if rc != OK:
            with self._mu:
                self._tasks.pop(tid, None)
            raise RuntimeError("WorkQueue.submit on stopped queue")

    def wait_idle(self):
        _lib.ptpu_wq_wait_idle(self._h)
        with self._mu:
            if self._errors:
                raise self._errors.pop(0)

    def pending(self) -> int:
        return int(_lib.ptpu_wq_pending(self._h))

    def shutdown(self):
        if self._h >= 0:
            _lib.ptpu_wq_destroy(self._h)
            self._h = -1

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


# ---- parameter server (≙ brpc PS: ps/service + memory tables) ----

_lib.ptpu_ps_server_start.restype = _i64
_lib.ptpu_ps_server_start.argtypes = [_i32]
_lib.ptpu_ps_server_port.restype = _i32
_lib.ptpu_ps_server_port.argtypes = [_i64]
_lib.ptpu_ps_server_stop.argtypes = [_i64]
_lib.ptpu_ps_client_create.restype = _i64
_lib.ptpu_ps_client_create.argtypes = [_chp, _i32, _dbl]
_lib.ptpu_ps_client_destroy.argtypes = [_i64]
_fltp = ctypes.POINTER(ctypes.c_float)
_u64p = ctypes.POINTER(ctypes.c_uint64)
_lib.ptpu_ps_create_dense.restype = _i32
_lib.ptpu_ps_create_dense.argtypes = [_i64, _i32, _i64]
_lib.ptpu_ps_create_sparse.restype = _i32
_lib.ptpu_ps_create_sparse.argtypes = [_i64, _i32, _i64, _dbl, _u64,
                                       ctypes.c_uint8, _dbl, _u64, _chp]
_lib.ptpu_ps_pull_dense.restype = _i32
_lib.ptpu_ps_pull_dense.argtypes = [_i64, _i32, _fltp, _i64]
_lib.ptpu_ps_set_dense.restype = _i32
_lib.ptpu_ps_set_dense.argtypes = [_i64, _i32, _fltp, _i64]
_lib.ptpu_ps_push_dense.restype = _i32
_lib.ptpu_ps_push_dense.argtypes = [_i64, _i32, _fltp, _i64, _dbl]
_lib.ptpu_ps_pull_sparse.restype = _i32
_lib.ptpu_ps_pull_sparse.argtypes = [_i64, _i32, _u64p, _i64, _i64, _fltp]
_lib.ptpu_ps_push_sparse.restype = _i32
_lib.ptpu_ps_push_sparse.argtypes = [_i64, _i32, _u64p, _i64, _i64, _fltp,
                                     _dbl]
_lib.ptpu_ps_sparse_size.restype = _i64
_lib.ptpu_ps_sparse_size.argtypes = [_i64, _i32]
_lib.ptpu_ps_sparse_mem_rows.restype = _i64
_lib.ptpu_ps_sparse_mem_rows.argtypes = [_i64, _i32]
_lib.ptpu_ps_create_graph.restype = _i32
_lib.ptpu_ps_create_graph.argtypes = [_i64, _i32, _u64]
_lib.ptpu_ps_graph_add_edges.restype = _i32
_lib.ptpu_ps_graph_add_edges.argtypes = [_i64, _i32, _u64p, _u64p, _i64]
_lib.ptpu_ps_graph_sample.restype = _i32
_lib.ptpu_ps_graph_sample.argtypes = [_i64, _i32, _u64p, _i64, _i64, _u64p]
_lib.ptpu_ps_graph_degree.restype = _i32
_lib.ptpu_ps_graph_degree.argtypes = [_i64, _i32, _u64p, _i64, _u64p]


class PSServerHandle:
    """In-process parameter-server (the reference runs brpc services;
    here a native TCP server thread owns the tables)."""

    def __init__(self, port: int = 0):
        self._h = _lib.ptpu_ps_server_start(port)
        if self._h < 0:
            raise OSError(f"PSServer: cannot bind port {port}")

    @property
    def port(self) -> int:
        return int(_lib.ptpu_ps_server_port(self._h))

    def stop(self):
        if self._h >= 0:
            _lib.ptpu_ps_server_stop(self._h)
            self._h = -1

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PSClientHandle:
    """One TCP connection to a PS server.  NOT thread-safe (the reference
    brpc client multiplexes; here use one client per thread)."""

    def __init__(self, host: str, port: int, timeout_s: float = 30.0):
        self._h = _lib.ptpu_ps_client_create(host.encode(), port, timeout_s)
        if self._h < 0:
            raise OSError(f"PSClient: cannot connect {host}:{port}")
        self._lock = threading.Lock()

    def close(self):
        if self._h >= 0:
            _lib.ptpu_ps_client_destroy(self._h)
            self._h = -1

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @staticmethod
    def _check(rc, what):
        if rc != OK:
            raise RuntimeError(f"parameter server: {what} failed (rc={rc})")

    def create_dense(self, table: int, dim: int):
        with self._lock:
            self._check(_lib.ptpu_ps_create_dense(self._h, table, dim),
                        "create_dense")

    def create_sparse(self, table: int, dim: int, init_scale: float = 0.0,
                      seed: int = 0, rule: int = 0, eps: float = 1e-8,
                      max_mem_rows: int = 0, spill_path: str = ""):
        with self._lock:
            self._check(
                _lib.ptpu_ps_create_sparse(
                    self._h, table, dim, init_scale, seed, rule, eps,
                    max_mem_rows,
                    spill_path.encode() if spill_path else None),
                "create_sparse")

    def sparse_mem_rows(self, table: int) -> int:
        with self._lock:
            n = int(_lib.ptpu_ps_sparse_mem_rows(self._h, table))
        if n < 0:
            raise RuntimeError("parameter server: sparse_mem_rows failed")
        return n

    def pull_dense(self, table: int, dim: int):
        import numpy as np
        out = np.empty(dim, np.float32)
        with self._lock:
            self._check(
                _lib.ptpu_ps_pull_dense(self._h, table,
                                        out.ctypes.data_as(_fltp), dim),
                "pull_dense")
        return out

    def set_dense(self, table: int, values):
        import numpy as np
        arr = np.ascontiguousarray(values, np.float32)
        with self._lock:
            self._check(
                _lib.ptpu_ps_set_dense(self._h, table,
                                       arr.ctypes.data_as(_fltp), arr.size),
                "set_dense")

    def push_dense(self, table: int, grad, lr: float):
        import numpy as np
        arr = np.ascontiguousarray(grad, np.float32)
        with self._lock:
            self._check(
                _lib.ptpu_ps_push_dense(self._h, table,
                                        arr.ctypes.data_as(_fltp),
                                        arr.size, lr),
                "push_dense")

    def pull_sparse(self, table: int, keys, dim: int):
        import numpy as np
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.empty((k.size, dim), np.float32)
        with self._lock:
            self._check(
                _lib.ptpu_ps_pull_sparse(self._h, table,
                                         k.ctypes.data_as(_u64p), k.size,
                                         dim, out.ctypes.data_as(_fltp)),
                "pull_sparse")
        return out

    def push_sparse(self, table: int, keys, grads, lr: float):
        import numpy as np
        k = np.ascontiguousarray(keys, np.uint64)
        g = np.ascontiguousarray(grads, np.float32)
        if g.shape[0] != k.size:
            raise ValueError(
                f"push_sparse: {k.size} keys but {g.shape[0]} grad rows")
        with self._lock:
            self._check(
                _lib.ptpu_ps_push_sparse(self._h, table,
                                         k.ctypes.data_as(_u64p), k.size,
                                         g.shape[1],
                                         g.ctypes.data_as(_fltp), lr),
                "push_sparse")

    def sparse_size(self, table: int) -> int:
        with self._lock:
            n = int(_lib.ptpu_ps_sparse_size(self._h, table))
        if n < 0:
            raise RuntimeError("parameter server: sparse_size failed")
        return n

    # graph tables (reference common_graph_table.h:501) ----------------
    def create_graph(self, table: int, seed: int = 0):
        with self._lock:
            self._check(_lib.ptpu_ps_create_graph(self._h, table, seed),
                        "create_graph")

    def graph_add_edges(self, table: int, src, dst):
        import numpy as np
        s = np.ascontiguousarray(src, np.uint64)
        d = np.ascontiguousarray(dst, np.uint64)
        if s.size != d.size:
            raise ValueError("graph_add_edges: src/dst length mismatch")
        with self._lock:
            self._check(
                _lib.ptpu_ps_graph_add_edges(
                    self._h, table, s.ctypes.data_as(_u64p),
                    d.ctypes.data_as(_u64p), s.size),
                "graph_add_edges")

    def graph_sample_neighbors(self, table: int, nodes, k: int):
        import numpy as np
        nd = np.ascontiguousarray(nodes, np.uint64)
        out = np.empty((nd.size, k), np.uint64)
        with self._lock:
            self._check(
                _lib.ptpu_ps_graph_sample(
                    self._h, table, nd.ctypes.data_as(_u64p), nd.size, k,
                    out.ctypes.data_as(_u64p)),
                "graph_sample_neighbors")
        return out

    def graph_degree(self, table: int, nodes):
        import numpy as np
        nd = np.ascontiguousarray(nodes, np.uint64)
        out = np.empty(nd.size, np.uint64)
        with self._lock:
            self._check(
                _lib.ptpu_ps_graph_degree(
                    self._h, table, nd.ctypes.data_as(_u64p), nd.size,
                    out.ctypes.data_as(_u64p)),
                "graph_degree")
        return out
