"""Profiler helpers (≙ python/paddle/profiler/utils.py)."""

from __future__ import annotations

import functools

from .profiler import RecordEvent


def record_function(name: str):
    """Decorator form of RecordEvent."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(name):
                return fn(*args, **kwargs)

        return wrapper

    return deco
