"""Profiler core (reference: python/paddle/profiler/profiler.py:349 over
paddle/fluid/platform/profiler/profiler.h:47).

The reference merges a host tracer and a CUPTI device tracer into an event
tree and exports chrome traces + summary tables. Here the host side is the
native C++ tracer (paddle_tpu.runtime.HostTracer); the device side is
jax.profiler (XLA xplane, viewable in TensorBoard/Perfetto), started and
stopped in lockstep when ``targets`` includes TPU.
"""

from __future__ import annotations

import enum
import os
from collections import defaultdict
from typing import Callable, Iterable, Optional

from .. import runtime as rt


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last RECORD step of a window: collect + return


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1   # accepted for API parity; maps to the XLA device tracer
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """State machine over step numbers (mirror of profiler.py:79).

    skip_first steps CLOSED, then cycles of [closed CLOSED, ready READY,
    record RECORD (last returns RECORD_AND_RETURN)]; ``repeat=0`` = cycle
    forever.
    """
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("make_scheduler: closed/ready >= 0 and record >= 1")
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD  # profile everything between start and stop


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback factory (≙ profiler.py:215)."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.json")
        rt.HostTracer.export_chrome_trace(path)
        prof._exported_paths.append(path)

    return handler


class RecordEvent:
    """User-scoped host range (≙ python/paddle/profiler/utils.py:38)."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def begin(self):
        rt.HostTracer.begin(self.name)

    def end(self):
        rt.HostTracer.end()


class _EventStat:
    __slots__ = ("count", "total_ns", "max_ns", "min_ns")

    def __init__(self):
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None

    def add(self, dur: int):
        self.count += 1
        self.total_ns += dur
        self.max_ns = max(self.max_ns, dur)
        self.min_ns = dur if self.min_ns is None else min(self.min_ns, dur)


class SummaryView:
    """Aggregated per-name host event table (≙ profiler_statistic.py)."""

    def __init__(self, events):
        self.stats = defaultdict(_EventStat)
        for kind, t0, t1, tid, value, name in events:
            if kind == 0:  # range
                self.stats[name].add(t1 - t0)

    def rows(self):
        out = []
        for name, s in sorted(self.stats.items(),
                              key=lambda kv: -kv[1].total_ns):
            out.append({
                "name": name, "calls": s.count,
                "total_ms": s.total_ns / 1e6,
                "avg_ms": s.total_ns / s.count / 1e6,
                "max_ms": s.max_ns / 1e6,
                "min_ms": (s.min_ns or 0) / 1e6,
            })
        return out

    def table(self) -> str:
        rows = self.rows()
        header = f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}" \
                 f"{'Max(ms)':>12}{'Min(ms)':>12}"
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r['name'][:39]:<40}{r['calls']:>8}{r['total_ms']:>12.3f}"
                f"{r['avg_ms']:>12.3f}{r['max_ms']:>12.3f}{r['min_ms']:>12.3f}")
        return "\n".join(lines)


def load_profiler_result(path: str):
    """Load an exported chrome trace back as a list of event dicts."""
    import json
    with open(path) as f:
        return json.load(f)["traceEvents"]


class DeviceSummaryView:
    """Per-op DEVICE-time statistics parsed from the jax.profiler capture
    (analogue of ``python/paddle/profiler/profiler_statistic.py``'s
    kernel/op summary tables).  Aggregates the XLA op events on the
    device lanes of the chrome trace that jax writes next to the xplane
    dump."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self._events = self._load(trace_dir)

    @staticmethod
    def _load(trace_dir):
        import glob
        import gzip
        import json

        events = []
        for path in glob.glob(os.path.join(
                trace_dir, "**", "*.trace.json.gz"), recursive=True):
            with gzip.open(path, "rt") as f:
                data = json.load(f)
            raw = data.get("traceEvents", [])
            # pid -> process name from metadata events
            pid_names = {}
            for e in raw:
                if e.get("ph") == "M" and e.get("name") == "process_name":
                    pid_names[e.get("pid")] = \
                        e.get("args", {}).get("name", "")
            device_pids = {p for p, n in pid_names.items()
                           if any(k in n for k in
                                  ("TPU", "GPU", "device", "Device"))}
            for e in raw:
                if e.get("ph") != "X" or "dur" not in e:
                    continue
                if device_pids and e.get("pid") not in device_pids:
                    continue
                events.append(e)
        return events

    def rows(self):
        stats = {}
        for e in self._events:
            name = e.get("name", "?")
            dur = float(e.get("dur", 0.0))  # microseconds
            s = stats.setdefault(name, [0, 0.0, 0.0, float("inf")])
            s[0] += 1
            s[1] += dur
            s[2] = max(s[2], dur)
            s[3] = min(s[3], dur)
        total = sum(s[1] for s in stats.values()) or 1.0
        out = []
        for name, (calls, tot, mx, mn) in sorted(
                stats.items(), key=lambda kv: -kv[1][1]):
            out.append({
                "name": name, "calls": calls,
                "total_ms": tot / 1e3, "avg_ms": tot / calls / 1e3,
                "max_ms": mx / 1e3, "min_ms": mn / 1e3,
                "ratio": tot / total,
            })
        return out

    def table(self, limit: int = 30) -> str:
        rows = self.rows()[:limit]
        header = (f"{'Device op':<48}{'Calls':>8}{'Total(ms)':>12}"
                  f"{'Avg(ms)':>12}{'Ratio':>8}")
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r['name'][:47]:<48}{r['calls']:>8}"
                f"{r['total_ms']:>12.3f}{r['avg_ms']:>12.3f}"
                f"{r['ratio']:>8.1%}")
        return "\n".join(lines)


class Profiler:
    """Reference-parity profiler driver.

    with Profiler(targets=[ProfilerTarget.CPU], scheduler=(2, 5)) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    print(p.summary().table())
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif callable(scheduler):
            self.scheduler = scheduler
        else:  # (start, end) tuple like the reference
            start, end = scheduler
            self.scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=1 if start >= 1 else 0,
                record=end - start, repeat=1)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_trace_dir = None
        self._device_tracing = False
        self._exported_paths: list = []
        self._events_snapshot = None

    # -- lifecycle --
    def start(self):
        self.current_state = self.scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._start_record()

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def step(self):
        prev = self.current_state
        self.step_num += 1
        new = self.scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev in recording and new not in recording:
            self._stop_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        elif prev not in recording and new in recording:
            self._start_record()
        self.current_state = new

    def _start_record(self):
        rt.HostTracer.clear()
        rt.HostTracer.enable()
        if not self.timer_only and any(
                t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                      ProfilerTarget.CUSTOM_DEVICE) for t in self.targets):
            import tempfile
            self._device_trace_dir = tempfile.mkdtemp(prefix="ptpu_xprof_")
            try:
                import jax
                jax.profiler.start_trace(self._device_trace_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _stop_record(self):
        rt.HostTracer.disable()
        self._events_snapshot = rt.HostTracer.events()
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results --
    def events(self):
        return self._events_snapshot or rt.HostTracer.events()

    def summary(self) -> SummaryView:
        return SummaryView(self.events())

    def export_chrome_trace(self, path: str):
        rt.HostTracer.export_chrome_trace(path)
        self._exported_paths.append(path)

    @property
    def device_trace_dir(self):
        """Directory with the XLA xplane dump (TensorBoard-viewable)."""
        return self._device_trace_dir

    def device_summary(self) -> "DeviceSummaryView":
        """Per-op device-time table from the capture (reference
        profiler_statistic.py kernel summary).  Requires a device target
        in ``targets`` and a completed record window."""
        if self._device_trace_dir is None:
            raise RuntimeError(
                "device_summary(): no device capture — profile with "
                "targets=[ProfilerTarget.TPU] and complete a record step")
        return DeviceSummaryView(self._device_trace_dir)
