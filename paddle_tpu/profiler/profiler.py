"""Profiler core (reference: python/paddle/profiler/profiler.py:349 over
paddle/fluid/platform/profiler/profiler.h:47).

The reference merges a host tracer and a CUPTI device tracer into an event
tree and exports chrome traces + summary tables. Here the host side is the
native C++ tracer (paddle_tpu.runtime.HostTracer); the device side is
jax.profiler (XLA xplane, viewable in TensorBoard/Perfetto), started and
stopped in lockstep when ``targets`` includes TPU.
"""

from __future__ import annotations

import enum
import os
from collections import defaultdict
from typing import Callable, Iterable, Optional

from .. import runtime as rt


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last RECORD step of a window: collect + return


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1   # accepted for API parity; maps to the XLA device tracer
    TPU = 2
    CUSTOM_DEVICE = 3


def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0) -> Callable:
    """State machine over step numbers (mirror of profiler.py:79).

    skip_first steps CLOSED, then cycles of [closed CLOSED, ready READY,
    record RECORD (last returns RECORD_AND_RETURN)]; ``repeat=0`` = cycle
    forever.
    """
    if closed < 0 or ready < 0 or record <= 0:
        raise ValueError("make_scheduler: closed/ready >= 0 and record >= 1")
    cycle = closed + ready + record

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def _default_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD  # profile everything between start and stop


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback factory (≙ profiler.py:215)."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof: "Profiler"):
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_step{prof.step_num}.json")
        rt.HostTracer.export_chrome_trace(path)
        prof._exported_paths.append(path)

    return handler


def _mismatch_counter():
    from ..observability import metrics as _obs
    return _obs.get_registry().counter(
        "profiler.record_event_mismatches",
        "RecordEvent.end() calls without a matching begin() "
        "(made no-ops instead of corrupting the tracer stack)")


class RecordEvent:
    """User-scoped host range (≙ python/paddle/profiler/utils.py:38).

    Begin/end are depth-guarded: ``end()`` without a matching
    ``begin()`` (including a double-``end()`` from explicit use plus
    ``__exit__``) is a no-op that warns and bumps the
    ``profiler.record_event_mismatches`` counter — an unmatched
    ``HostTracer.end()`` would otherwise pop someone ELSE's range off
    the per-thread tracer stack and silently corrupt the trace."""

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        # one entry per OPEN range: the trace generation it was opened
        # in (a plain depth int + single gen would let a re-begin()
        # inside a new window launder a stale open across the boundary)
        self._opens: list = []

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        # exiting a with-block whose range was already closed by an
        # explicit end() is the documented early-stop idiom — close
        # only if this instance still owns an open range, never warn
        if self._opens:
            self._pop_if_same_window()
        return False

    def _pop_if_same_window(self):
        """Pop the tracer range unless a record-window boundary since
        its begin() invalidated it (popping then would close an
        unrelated range from the NEW window)."""
        from ..observability import spans as _spans
        if self._opens.pop() == _spans.current_trace_generation():
            rt.HostTracer.end()
        else:
            _mismatch_counter().inc()

    def begin(self):
        # only ranges the tracer actually opened are tracked: a
        # begin() outside a profiling window pushes nothing, so a later
        # end() INSIDE a window must not pop an unrelated range
        if rt.HostTracer.enabled:
            from ..observability import spans as _spans
            self._opens.append(_spans.current_trace_generation())
            rt.HostTracer.begin(self.name)

    def end(self):
        if self._opens:
            self._pop_if_same_window()
            return
        # depth 0 with tracing OFF is the normal un-profiled path (the
        # paired begin() counted nothing) — only an in-window unmatched
        # end() is a caller bug worth warning about
        if rt.HostTracer.enabled:
            import warnings
            _mismatch_counter().inc()
            warnings.warn(
                f"RecordEvent({self.name!r}).end() without a matching "
                f"begin(); ignored", RuntimeWarning, stacklevel=2)


class _EventStat:
    __slots__ = ("count", "total_ns", "max_ns", "min_ns", "self_ns",
                 "instants")

    def __init__(self):
        self.count = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None
        self.self_ns = 0
        self.instants = 0

    def add(self, dur: int, self_ns: int):
        self.count += 1
        self.total_ns += dur
        self.self_ns += self_ns
        self.max_ns = max(self.max_ns, dur)
        self.min_ns = dur if self.min_ns is None else min(self.min_ns, dur)


class SummaryView:
    """Aggregated per-name host event table (≙ profiler_statistic.py).

    ``total`` for a name sums its ranges INCLUSIVE of children (so a
    parent scope double-counts its nested ranges there — that is the
    chrome-trace convention); ``self`` subtracts each range's DIRECT
    children, so the self column partitions wall time without double
    counting.  Instant events are tallied per name as zero-duration
    occurrences instead of being dropped.  Span attr suffixes
    (``name;k=v`` from ``observability.spans``) are stripped before
    aggregation, so 100 ``serving.prefill`` spans with distinct request
    ids land in ONE row, not 100."""

    def __init__(self, events):
        from ..observability.spans import parse_span_name
        self.stats = defaultdict(_EventStat)
        per_tid = defaultdict(list)
        for kind, t0, t1, tid, value, name in events:
            name = parse_span_name(name)[0]
            if kind == 0:  # range
                per_tid[tid].append((t0, t1, name))
            elif kind == 1:  # instant
                self.stats[name].instants += 1
        for ranges in per_tid.values():
            # sweep in start order (ties: widest first = parent first);
            # a stack entry is [t1, child_ns, t0, name] and child time
            # is charged to the DIRECT parent only
            stack = []

            def close(entry):
                t1, child_ns, t0, name = entry
                dur = t1 - t0
                self.stats[name].add(dur, max(dur - child_ns, 0))

            for t0, t1, name in sorted(ranges,
                                       key=lambda r: (r[0], -r[1])):
                while stack and stack[-1][0] <= t0:
                    close(stack.pop())
                if stack:
                    stack[-1][1] += t1 - t0
                stack.append([t1, 0, t0, name])
            while stack:
                close(stack.pop())

    def rows(self):
        out = []
        for name, s in sorted(self.stats.items(),
                              key=lambda kv: -kv[1].total_ns):
            out.append({
                "name": name, "calls": s.count,
                "total_ms": s.total_ns / 1e6,
                "self_ms": s.self_ns / 1e6,
                "avg_ms": (s.total_ns / s.count / 1e6) if s.count else 0.0,
                "max_ms": s.max_ns / 1e6,
                "min_ms": (s.min_ns or 0) / 1e6,
                "instants": s.instants,
            })
        return out

    def table(self) -> str:
        rows = self.rows()
        header = f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}" \
                 f"{'Self(ms)':>12}{'Avg(ms)':>12}" \
                 f"{'Max(ms)':>12}{'Min(ms)':>12}"
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r['name'][:39]:<40}{r['calls']:>8}{r['total_ms']:>12.3f}"
                f"{r['self_ms']:>12.3f}"
                f"{r['avg_ms']:>12.3f}{r['max_ms']:>12.3f}{r['min_ms']:>12.3f}")
        return "\n".join(lines)


def load_profiler_result(path: str):
    """Load an exported chrome trace back as a list of event dicts."""
    import json
    with open(path) as f:
        return json.load(f)["traceEvents"]


class DeviceSummaryView:
    """Per-op DEVICE-time statistics parsed from the jax.profiler capture
    (analogue of ``python/paddle/profiler/profiler_statistic.py``'s
    kernel/op summary tables).  Aggregates the XLA op events on the
    device lanes of the chrome trace that jax writes next to the xplane
    dump."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self._events = self._load(trace_dir)

    @staticmethod
    def _load(trace_dir):
        import glob
        import gzip
        import json

        events = []
        for path in glob.glob(os.path.join(
                trace_dir, "**", "*.trace.json.gz"), recursive=True):
            with gzip.open(path, "rt") as f:
                data = json.load(f)
            raw = data.get("traceEvents", [])
            # pid -> process name from metadata events
            pid_names = {}
            for e in raw:
                if e.get("ph") == "M" and e.get("name") == "process_name":
                    pid_names[e.get("pid")] = \
                        e.get("args", {}).get("name", "")
            device_pids = {p for p, n in pid_names.items()
                           if any(k in n for k in
                                  ("TPU", "GPU", "device", "Device"))}
            for e in raw:
                if e.get("ph") != "X" or "dur" not in e:
                    continue
                if device_pids and e.get("pid") not in device_pids:
                    continue
                events.append(e)
        return events

    def rows(self):
        stats = {}
        for e in self._events:
            name = e.get("name", "?")
            dur = float(e.get("dur", 0.0))  # microseconds
            s = stats.setdefault(name, [0, 0.0, 0.0, float("inf")])
            s[0] += 1
            s[1] += dur
            s[2] = max(s[2], dur)
            s[3] = min(s[3], dur)
        total = sum(s[1] for s in stats.values()) or 1.0
        out = []
        for name, (calls, tot, mx, mn) in sorted(
                stats.items(), key=lambda kv: -kv[1][1]):
            out.append({
                "name": name, "calls": calls,
                "total_ms": tot / 1e3, "avg_ms": tot / calls / 1e3,
                "max_ms": mx / 1e3, "min_ms": mn / 1e3,
                "ratio": tot / total,
            })
        return out

    def table(self, limit: int = 30) -> str:
        rows = self.rows()[:limit]
        header = (f"{'Device op':<48}{'Calls':>8}{'Total(ms)':>12}"
                  f"{'Avg(ms)':>12}{'Ratio':>8}")
        lines = [header, "-" * len(header)]
        for r in rows:
            lines.append(
                f"{r['name'][:47]:<48}{r['calls']:>8}"
                f"{r['total_ms']:>12.3f}{r['avg_ms']:>12.3f}"
                f"{r['ratio']:>8.1%}")
        return "\n".join(lines)


class Profiler:
    """Reference-parity profiler driver.

    with Profiler(targets=[ProfilerTarget.CPU], scheduler=(2, 5)) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    print(p.summary().table())
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 timer_only: bool = False, profile_memory: bool = False,
                 with_flops: bool = False):
        self.targets = list(targets or [ProfilerTarget.CPU])
        if scheduler is None:
            self.scheduler = _default_scheduler
        elif callable(scheduler):
            self.scheduler = scheduler
        else:  # (start, end) tuple like the reference
            start, end = scheduler
            self.scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=1 if start >= 1 else 0,
                record=end - start, repeat=1)
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._device_trace_dir = None
        self._device_tracing = False
        self._exported_paths: list = []
        self._events_snapshot = None

    # -- lifecycle --
    def start(self):
        self.current_state = self.scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._start_record()

    def stop(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def step(self):
        prev = self.current_state
        self.step_num += 1
        new = self.scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if prev in recording and new not in recording:
            self._stop_record()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        elif prev not in recording and new in recording:
            self._start_record()
        self.current_state = new

    def _start_record(self):
        from ..observability import spans as _spans
        rt.HostTracer.clear()
        # invalidate ranges opened in any previous window: their tracer
        # stack entries did not survive the clear/disable boundary
        _spans.bump_trace_generation()
        rt.HostTracer.enable()
        if not self.timer_only and any(
                t in (ProfilerTarget.TPU, ProfilerTarget.GPU,
                      ProfilerTarget.CUSTOM_DEVICE) for t in self.targets):
            import tempfile
            self._device_trace_dir = tempfile.mkdtemp(prefix="ptpu_xprof_")
            try:
                import jax
                jax.profiler.start_trace(self._device_trace_dir)
                self._device_tracing = True
            except Exception:
                self._device_tracing = False

    def _stop_record(self):
        rt.HostTracer.disable()
        self._events_snapshot = rt.HostTracer.events()
        if self._device_tracing:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._device_tracing = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- results --
    def events(self):
        return self._events_snapshot or rt.HostTracer.events()

    def summary(self) -> SummaryView:
        return SummaryView(self.events())

    def metrics(self) -> dict:
        """Snapshot of the process-wide observability registry
        (serving/train-step/kernel-dispatch instruments) — the
        always-on counters that complement the windowed event trace."""
        from ..observability import metrics as _obs
        return _obs.get_registry().snapshot()

    def export_merged_trace(self, path: str) -> dict:
        """Stitch the recorded host events and the device capture (when
        a device target completed a record window) into ONE
        Perfetto-loadable chrome trace at ``path``."""
        from ..observability.spans import merge_chrome_traces
        return merge_chrome_traces(
            path, host=self.events(),
            device_trace_dir=self._device_trace_dir)

    def export_chrome_trace(self, path: str):
        rt.HostTracer.export_chrome_trace(path)
        self._exported_paths.append(path)

    @property
    def device_trace_dir(self):
        """Directory with the XLA xplane dump (TensorBoard-viewable)."""
        return self._device_trace_dir

    def device_summary(self) -> "DeviceSummaryView":
        """Per-op device-time table from the capture (reference
        profiler_statistic.py kernel summary).  Requires a device target
        in ``targets`` and a completed record window."""
        if self._device_trace_dir is None:
            raise RuntimeError(
                "device_summary(): no device capture — profile with "
                "targets=[ProfilerTarget.TPU] and complete a record step")
        return DeviceSummaryView(self._device_trace_dir)
