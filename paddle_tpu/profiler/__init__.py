"""paddle_tpu.profiler — profiling API.

Analogue of ``python/paddle/profiler/profiler.py:349`` (Profiler with
state scheduler, ``export_chrome_tracing``, summary tables) over two
backends:

- the native :class:`~paddle_tpu.runtime.HostTracer` (C++ per-thread event
  buffers ≙ host_event_recorder.h) records host ranges — op dispatch,
  dataloader, user ``RecordEvent`` scopes;
- ``jax.profiler`` (XLA/TPU xplane tracer ≙ CudaTracer/CUPTI) captures the
  device side when a trace dir is given.
"""

from .profiler import (  # noqa: F401
    DeviceSummaryView, Profiler, ProfilerState, ProfilerTarget,
    RecordEvent, SummaryView,
    make_scheduler, export_chrome_tracing, load_profiler_result,
)
from .utils import record_function  # noqa: F401

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "SummaryView", "DeviceSummaryView", "make_scheduler",
    "export_chrome_tracing",
    "load_profiler_result", "record_function",
]
