"""paddle_tpu.sparse — COO/CSR sparse tensors and ops.

Analogue of ``python/paddle/sparse/`` over the reference's
SparseCooTensor/SparseCsrTensor (paddle/phi/core/sparse_coo_tensor.h,
SURVEY §2.1). TPU-native design: backed by jax.experimental.sparse
BCOO/BCSR — XLA lowers sparse matmuls to gather/scatter+MXU-dense blocks,
which is the right TPU formulation (no cuSPARSE analogue needed).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "matmul", "add", "multiply",
    "masked_matmul", "relu", "nn",
]


class SparseCooTensor:
    """COO sparse tensor (indices [ndim, nnz] like the reference)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # reference layout [ndim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._bcoo))

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    def __init__(self, bcsr: jsparse.BCSR):
        self._bcsr = bcsr

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    def crows(self) -> Tensor:
        return Tensor(self._bcsr.indptr)

    def cols(self) -> Tensor:
        return Tensor(self._bcsr.indices)

    def values(self) -> Tensor:
        return Tensor(self._bcsr.data)

    def nnz(self) -> int:
        return int(self._bcsr.nse)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=None) -> SparseCooTensor:
        return SparseCooTensor(self._bcsr.to_bcoo())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _as_array(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def sparse_coo_tensor(indices, values, shape: Optional[Sequence[int]] = None,
                      dtype=None, place=None, stop_gradient=True):
    """Build a COO tensor from [ndim, nnz] indices + [nnz] values
    (reference paddle.sparse.sparse_coo_tensor)."""
    idx = np.asarray(_as_array(indices)).T  # -> [nnz, ndim]
    vals = _as_array(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None):
    vals = _as_array(values)
    if dtype is not None:
        from ..core.dtypes import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    crows_a = _as_array(crows).astype(jnp.int32)
    cols_a = _as_array(cols).astype(jnp.int32)
    if len(shape) == 3 and crows_a.ndim == 1:
        # paddle convention: batched CSR arrives flattened
        # (crows [b*(rows+1)], cols/values [total_nnz]); jax BCSR wants
        # batch-shaped components with UNIFORM per-batch nnz
        b, rows = int(shape[0]), int(shape[1])
        crows_a = crows_a.reshape(b, rows + 1)
        per = np.asarray(crows_a[:, -1])
        if not (per == per[0]).all():
            raise ValueError(
                "batched CSR needs a uniform nnz per batch on TPU "
                "(jax BCSR layout); pad rows or use COO")
        cols_a = cols_a.reshape(b, -1)
        vals = vals.reshape(b, -1)
    bcsr = jsparse.BCSR((vals, cols_a, crows_a), shape=tuple(shape))
    return SparseCsrTensor(bcsr)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def _lift(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x._bcsr
    return _as_array(x)


def matmul(x, y, name=None):
    """sparse @ dense (or sparse @ sparse -> dense)."""
    a, b = _lift(x), _lift(y)
    out = a @ b
    if isinstance(out, (jsparse.BCOO, jsparse.BCSR)):
        out = out.todense()
    return Tensor(out)


def masked_matmul(x, y, mask: SparseCooTensor, name=None):
    """dense @ dense evaluated only at mask's nonzeros (SDDMM)."""
    a, b = _as_array(x), _as_array(y)
    out = jsparse.bcoo_dot_general_sampled(
        a, b, mask._bcoo.indices,
        dimension_numbers=(((a.ndim - 1,), (0,)), ((), ())))
    return SparseCooTensor(
        jsparse.BCOO((out, mask._bcoo.indices), shape=mask._bcoo.shape))


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return SparseCooTensor((x._bcoo + y._bcoo).sum_duplicates())
    return Tensor(_lift(x).todense() + _as_array(y))


def multiply(x, y, name=None):
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        # elementwise with dense: scale values at nonzero coords
        dense_vals = _as_array(y)[tuple(x._bcoo.indices.T)]
        return SparseCooTensor(jsparse.BCOO(
            (x._bcoo.data * dense_vals, x._bcoo.indices),
            shape=x._bcoo.shape))
    return Tensor(_lift(x).todense() * _as_array(y))


def relu(x, name=None):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(jsparse.BCOO(
            (jnp.maximum(x._bcoo.data, 0), x._bcoo.indices),
            shape=x._bcoo.shape))
    return Tensor(jnp.maximum(_as_array(x), 0))


from . import nn  # noqa: E402,F401  (full sparse.nn layer tree)
