"""paddle.sparse.nn layer tree (analogue of
``python/paddle/sparse/nn/layer/``: conv.py Conv3D:239/SubmConv3D:509/
Conv2D:374/SubmConv2D:649, norm.py BatchNorm:24/SyncBatchNorm:207,
pooling.py MaxPool3D:20, activation.py)."""

from __future__ import annotations

import numpy as np

from ...nn.layer.layers import Layer
from . import functional  # noqa: F401
from . import functional as F

__all__ = ["Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "BatchNorm",
           "SyncBatchNorm", "MaxPool3D", "ReLU", "ReLU6", "LeakyReLU",
           "Softmax"]


class _SparseConvNd(Layer):
    _subm = False
    _ndim = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 key=None):
        super().__init__()
        if isinstance(kernel_size, int):
            kernel_size = (kernel_size,) * self._ndim
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        from ...nn.initializer import XavierUniform
        self.weight = self.create_parameter(
            (*self.kernel_size, in_channels, out_channels),
            attr=weight_attr, default_initializer=XavierUniform())
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter((out_channels,),
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        fn = {(2, False): F.conv2d, (2, True): F.subm_conv2d,
              (3, False): F.conv3d, (3, True): F.subm_conv3d}[
                  (self._ndim, self._subm)]
        # dilation/groups pass through so non-default values raise the
        # functional's NotImplementedError instead of silently dropping
        return fn(x, self.weight, self.bias, self.stride, self.padding,
                  self.dilation, self.groups)


class Conv3D(_SparseConvNd):
    _ndim, _subm = 3, False


class SubmConv3D(_SparseConvNd):
    _ndim, _subm = 3, True


class Conv2D(_SparseConvNd):
    _ndim, _subm = 2, False


class SubmConv2D(_SparseConvNd):
    _ndim, _subm = 2, True


class BatchNorm(Layer):
    """Batch norm over the stored values' channel dim (reference sparse
    BatchNorm subclasses dense BatchNorm1D on values — statistics run
    over ACTIVE sites only, by design)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr,
                               use_global_stats=use_global_stats)

    def forward(self, x):
        import jax.experimental.sparse as jsparse

        from .. import SparseCooTensor
        from ...core.tensor import Tensor
        vals = self._bn(Tensor(x._bcoo.data))
        return SparseCooTensor(jsparse.BCOO(
            (vals._value.astype(x._bcoo.data.dtype), x._bcoo.indices),
            shape=x._bcoo.shape))


class SyncBatchNorm(BatchNorm):
    """On TPU, batch-norm stats inside pjit already reduce across the data
    axis (GSPMD inserts the cross-replica psum) — SyncBatchNorm is the
    default semantics, so this is BatchNorm (reference norm.py:207)."""


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding

    def forward(self, x):
        return F.max_pool3d(x, self.kernel_size, self.stride, self.padding)


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)
