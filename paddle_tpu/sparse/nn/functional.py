"""Sparse-NN functionals over BCOO (analogue of
``python/paddle/sparse/nn/functional/``: conv.py:207/313/425/529,
pooling.py:22, transformer.py:22, activation.py).

TPU-native formulation: sparse convolutions use a host-built RULEBOOK
(the same structure the reference's GPU kernels build on device,
``paddle/phi/kernels/sparse/gpu/conv_kernel.cu``) — for each kernel
offset, the (input-site -> output-site) pairs are gathered once on the
host from the COO coordinates, and the COMPUTE is a batched
gather + [n_k, Cin] @ [Cin, Cout] matmul + scatter-add per offset, which
rides the MXU.  Coordinates are data-dependent, so these ops are
EAGER-ONLY (like every dynamic-output-shape op in this framework); the
dense-masked attention path is fully traceable.

Layout follows the reference: activations are channels-LAST
(``[N, D, H, W, C]`` dense shape, indices over the leading dims), conv
weights are ``[*kernel, C_in, C_out]``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor
from .. import SparseCooTensor, sparse_coo_tensor

__all__ = ["conv2d", "conv3d", "subm_conv2d", "subm_conv3d", "max_pool3d",
           "relu", "relu6", "leaky_relu", "softmax", "attention"]


def _norm_seq(v, n):
    if isinstance(v, (list, tuple)):
        out = [int(i) for i in v]
        return out * n if len(out) == 1 else out
    return [int(v)] * n


def _coords_values(x: SparseCooTensor):
    """Host coordinates [nnz, k] + device values [nnz, C]."""
    bcoo = x._bcoo
    coords = np.asarray(bcoo.indices)
    vals = bcoo.data
    if vals.ndim == 1:
        raise ValueError(
            "sparse nn ops expect the channels-dense COO layout: indices "
            "over [N, *spatial], values [nnz, C] (build via "
            "sparse_coo_tensor with [1+spatial, nnz] indices and 2-D "
            "values)")
    return coords, vals


def _assert_eager(coords, name):
    if not isinstance(coords, np.ndarray):
        raise NotImplementedError(
            f"sparse {name} builds its rulebook from concrete coordinates "
            "and cannot run under jit/trace (reference GPU rulebook "
            "construction is likewise data-dependent)")


def _rulebook_conv(x: SparseCooTensor, weight, bias, stride, padding,
                   subm: bool, name: str, dilation=1, groups=1):
    """Shared sparse-conv engine.  x dense shape [N, *spatial, Cin];
    weight [*kernel, Cin/groups, Cout].  Dilation scales the rulebook's
    offset enumeration; groups block the channel matmul (reference
    kernel takes both: ``paddle/phi/kernels/sparse/gpu/conv_kernel.cu:75``)."""
    n_sp = weight.ndim - 2
    kernel = weight.shape[:n_sp]
    stride = _norm_seq(stride, n_sp)
    padding = _norm_seq(padding, n_sp)
    dilation = _norm_seq(dilation, n_sp)
    groups = int(groups)
    if subm and any(s != 1 for s in stride):
        raise ValueError(f"{name}: submanifold conv requires stride 1")

    coords, vals = _coords_values(x)
    _assert_eager(coords, name)
    dense_shape = x.shape
    spatial = dense_shape[1:1 + n_sp]
    cout = weight.shape[-1]
    cin = dense_shape[-1]
    if groups < 1 or cin % groups or cout % groups:
        raise ValueError(
            f"{name}: groups ({groups}) must divide C_in ({cin}) and "
            f"C_out ({cout})")
    if weight.shape[-2] * groups != cin:
        raise ValueError(
            f"{name}: weight C_in/groups dim ({weight.shape[-2]}) != "
            f"C_in/groups ({cin}//{groups})")

    if subm:
        out_spatial = list(spatial)
        out_coords = coords
    else:
        out_spatial = [
            (spatial[i] + 2 * padding[i]
             - dilation[i] * (kernel[i] - 1) - 1) // stride[i] + 1
            for i in range(n_sp)]

    def keys_of(c_arr, sp):
        # batch-major mixed radix site key
        key = c_arr[:, 0].astype(np.int64)
        for i in range(n_sp):
            key = key * sp[i] + c_arr[:, 1 + i].astype(np.int64)
        return key

    # ONE pass builds the rulebook: for each kernel offset, the
    # (input row, output site key) pairs that contribute through it
    offsets = list(np.ndindex(*kernel))
    in_sp = coords[:, 1:1 + n_sp].astype(np.int64)
    batch = coords[:, 0].astype(np.int64)
    rule = []  # per offset: (src_rows, out_keys) or None
    for off in offsets:
        # dilation scales each kernel offset's spatial displacement
        oc = in_sp + np.asarray(padding) - np.asarray(off) * \
            np.asarray(dilation)
        ok = np.ones(len(coords), bool)
        for i in range(n_sp):
            ok &= (oc[:, i] % stride[i] == 0)
        oc2 = oc // np.asarray(stride)
        for i in range(n_sp):
            ok &= (oc2[:, i] >= 0) & (oc2[:, i] < out_spatial[i])
        if not ok.any():
            rule.append(None)
            continue
        okey = batch[ok]
        for i in range(n_sp):
            okey = okey * out_spatial[i] + oc2[ok, i]
        rule.append((np.nonzero(ok)[0], okey))

    if subm:
        out_keys = keys_of(coords, spatial)
        order = np.argsort(out_keys, kind="stable")
        sorted_keys = out_keys[order]
        n_out = coords.shape[0]
    else:
        # output sites = union of keys the rulebook reaches
        all_keys = np.unique(np.concatenate(
            [r[1] for r in rule if r is not None] or
            [np.zeros(0, np.int64)]))
        sorted_keys, order = all_keys, np.arange(len(all_keys))
        n_out = len(all_keys)
        # decode keys back to coordinates (batch-major mixed radix)
        out_coords = np.zeros((n_out, n_sp + 1), np.int64)
        rem = all_keys.copy()
        for i in range(n_sp - 1, -1, -1):
            out_coords[:, 1 + i] = rem % out_spatial[i]
            rem = rem // out_spatial[i]
        out_coords[:, 0] = rem

    out_vals = jnp.zeros((max(n_out, 1), cout),
                         jnp.result_type(vals.dtype, weight.dtype))
    w = weight.reshape((-1,) + weight.shape[n_sp:])
    cin_g = cin // groups
    cout_g = cout // groups
    for oi, r in enumerate(rule):
        if r is None:
            continue
        src, okeys = r
        # vectorized key -> row resolution (a python dict lookup here is
        # O(kernel_volume * nnz) interpreted ops per forward)
        pos = np.searchsorted(sorted_keys, okeys)
        pos = np.clip(pos, 0, len(sorted_keys) - 1)
        hit = sorted_keys[pos] == okeys if len(sorted_keys) else \
            np.zeros(len(okeys), bool)
        tgt = np.where(hit, order[pos], -1)
        sel = tgt >= 0
        if not sel.any():
            continue
        gathered = vals[jnp.asarray(src[sel])]
        if groups == 1:
            contrib = gathered @ w[oi]
        else:
            # blocked channel matmul: group g's input slice hits its own
            # [cin_g, cout_g] weight block (output channels partitioned
            # into consecutive per-group blocks, the dense convention)
            contrib = jnp.einsum(
                "ngc,cgo->ngo",
                gathered.reshape(-1, groups, cin_g),
                w[oi].reshape(cin_g, groups, cout_g)).reshape(-1, cout)
        out_vals = out_vals.at[jnp.asarray(tgt[sel])].add(
            contrib.astype(out_vals.dtype))
    if bias is not None:
        b = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        out_vals = out_vals + b
    out_shape = (dense_shape[0], *out_spatial, cout)
    return sparse_coo_tensor(
        np.ascontiguousarray(out_coords.T), out_vals[:n_out],
        shape=out_shape)


def _weight_arr(weight):
    return weight._value if isinstance(weight, Tensor) else \
        jnp.asarray(weight)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse conv3d (reference sparse/nn/functional/conv.py:207)."""
    return _rulebook_conv(x, _weight_arr(weight), bias, stride, padding,
                          subm=False, name="conv3d", dilation=dilation,
                          groups=groups)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold sparse conv3d: output sites == input sites
    (reference sparse/nn/functional/conv.py:313)."""
    return _rulebook_conv(x, _weight_arr(weight), bias, stride, padding,
                          subm=True, name="subm_conv3d", dilation=dilation,
                          groups=groups)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    return _rulebook_conv(x, _weight_arr(weight), bias, stride, padding,
                          subm=False, name="conv2d", dilation=dilation,
                          groups=groups)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _rulebook_conv(x, _weight_arr(weight), bias, stride, padding,
                          subm=True, name="subm_conv2d", dilation=dilation,
                          groups=groups)


def max_pool3d(x, kernel_size, stride=None, padding=0,
               data_format="NDHWC", name=None):
    """Sparse max pooling over active sites (reference
    sparse/nn/functional/pooling.py:22)."""
    kernel = _norm_seq(kernel_size, 3)
    stride = _norm_seq(stride if stride is not None else kernel_size, 3)
    padding = _norm_seq(padding, 3)
    coords, vals = _coords_values(x)
    _assert_eager(coords, "max_pool3d")
    dense_shape = x.shape
    spatial = dense_shape[1:4]
    out_spatial = [
        (spatial[i] + 2 * padding[i] - kernel[i]) // stride[i] + 1
        for i in range(3)]

    # each active input site maps into every window that covers it;
    # reductions run as ONE segment_max over all (src, window) pairs
    in_sp = coords[:, 1:4].astype(np.int64)
    batch = coords[:, 0].astype(np.int64)
    srcs, okeys = [], []
    for off in np.ndindex(*kernel):
        oc = in_sp + np.asarray(padding) - np.asarray(off)
        ok = np.ones(len(coords), bool)
        for i in range(3):
            ok &= (oc[:, i] % stride[i] == 0)
        oc2 = oc // np.asarray(stride)
        for i in range(3):
            ok &= (oc2[:, i] >= 0) & (oc2[:, i] < out_spatial[i])
        if not ok.any():
            continue
        key = batch[ok]
        for i in range(3):
            key = key * out_spatial[i] + oc2[ok, i]
        srcs.append(np.nonzero(ok)[0])
        okeys.append(key)
    if not srcs:
        out_coords = np.zeros((4, 0), np.int64)
        out_vals = jnp.zeros((0, dense_shape[-1]), vals.dtype)
    else:
        src = np.concatenate(srcs)
        key = np.concatenate(okeys)
        uniq, seg = np.unique(key, return_inverse=True)
        out_vals = jax.ops.segment_max(vals[jnp.asarray(src)],
                                       jnp.asarray(seg),
                                       num_segments=len(uniq))
        out_coords = np.zeros((len(uniq), 4), np.int64)
        rem = uniq.copy()
        for i in range(2, -1, -1):
            out_coords[:, 1 + i] = rem % out_spatial[i]
            rem = rem // out_spatial[i]
        out_coords[:, 0] = rem
        out_coords = out_coords.T
    return sparse_coo_tensor(
        out_coords, out_vals,
        shape=(dense_shape[0], *out_spatial, dense_shape[-1]))


def relu(x, name=None):
    from .. import relu as _relu
    return _relu(x)


def relu6(x, name=None):
    b = x._bcoo
    import jax.experimental.sparse as jsparse
    return SparseCooTensor(jsparse.BCOO(
        (jnp.clip(b.data, 0, 6), b.indices), shape=b.shape))


def leaky_relu(x, negative_slope=0.01, name=None):
    b = x._bcoo
    import jax.experimental.sparse as jsparse
    return SparseCooTensor(jsparse.BCOO(
        (jnp.where(b.data > 0, b.data, negative_slope * b.data),
         b.indices), shape=b.shape))


def softmax(x, axis=-1, name=None):
    """Row-wise softmax over the stored values (reference sparse softmax
    semantics: normalize over the nonzeros of each row of the last two
    dense dims)."""
    from .. import SparseCsrTensor
    if isinstance(x, SparseCsrTensor):
        crows = np.asarray(x.crows()._value)
        vals = x.values()._value
        if crows.ndim == 2:  # batched [B, S, S] CSR
            b = crows.shape[0]
            segs = np.diff(crows, axis=1)          # [B, S]
            flat_segs = segs.reshape(-1)
            row_ids = np.repeat(np.arange(flat_segs.size), flat_segs)
            flat_vals = vals.reshape(-1)
            r = jnp.asarray(row_ids)
            n_rows = flat_segs.size
            mx = jax.ops.segment_max(flat_vals, r, num_segments=n_rows)
            e = jnp.exp(flat_vals - mx[r])
            den = jax.ops.segment_sum(e, r, num_segments=n_rows)
            out_vals = (e / den[r]).reshape(vals.shape)
            from .. import sparse_csr_tensor
            return sparse_csr_tensor(crows.reshape(-1),
                                     np.asarray(x.cols()._value).reshape(-1),
                                     out_vals.reshape(-1), x.shape)
        segs = np.diff(crows)
        row_ids = np.repeat(np.arange(len(segs)), segs)
        r = jnp.asarray(row_ids)
        mx = jax.ops.segment_max(vals, r, num_segments=len(segs))
        e = jnp.exp(vals - mx[r])
        den = jax.ops.segment_sum(e, r, num_segments=len(segs))
        out_vals = e / den[r]
        from .. import sparse_csr_tensor
        return sparse_csr_tensor(crows, x.cols()._value, out_vals, x.shape)
    coords, vals = _coords_values(x)
    _assert_eager(coords, "softmax")
    # group by all but the last sparse dim
    keys = [tuple(map(int, row[:-1])) for row in coords]
    uniq = {k: i for i, k in enumerate(dict.fromkeys(keys))}
    row_ids = np.asarray([uniq[k] for k in keys])
    r = jnp.asarray(row_ids)
    n_rows = len(uniq)
    mx = jax.ops.segment_max(vals, r, num_segments=n_rows)
    e = jnp.exp(vals - mx[r])
    den = jax.ops.segment_sum(e, r, num_segments=n_rows)
    out_vals = e / den[r]
    import jax.experimental.sparse as jsparse
    return SparseCooTensor(jsparse.BCOO((out_vals, x._bcoo.indices),
                                        shape=x._bcoo.shape))


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse attention (reference sparse/nn/functional/transformer.py:22):
    scores are computed only where ``sparse_mask`` (CSR, [B*H, S, S]) has
    entries.  TPU-native: dense-masked QK^T — the mask pattern becomes an
    additive -inf mask, softmax/AV run dense (the fast path on MXU);
    results match the reference's sparse kernel at the stored positions.
    """
    q = query._value if isinstance(query, Tensor) else jnp.asarray(query)
    k = key._value if isinstance(key, Tensor) else jnp.asarray(key)
    v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
    b, h, s, d = q.shape
    crows = np.asarray(sparse_mask.crows()._value).reshape(b * h, s + 1)
    cols = np.asarray(sparse_mask.cols()._value).reshape(b * h, -1)
    mask = np.zeros((b * h, s, s), bool)
    per = crows[:, -1]
    for i in range(b * h):
        my_cols = cols[i, :per[i]]
        rows = np.repeat(np.arange(s), np.diff(crows[i]))
        mask[i, rows, my_cols] = True
    mask = jnp.asarray(mask.reshape(b, h, s, s))
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(d)
    neg = jnp.asarray(-1e30, scores.dtype)
    scores = jnp.where(mask, scores, neg)
    if key_padding_mask is not None:
        kp = key_padding_mask._value if isinstance(key_padding_mask,
                                                   Tensor) else \
            jnp.asarray(key_padding_mask)
        scores = scores + kp[:, None, None, :].astype(scores.dtype)
    if attn_mask is not None:
        am = attn_mask._value if isinstance(attn_mask, Tensor) else \
            jnp.asarray(attn_mask)
        scores = scores + am.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(mask, probs, 0.0)  # fully-masked rows -> zeros
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v)
    return Tensor(out)
