"""paddle_tpu.autograd — autograd facade (analogue of paddle.autograd).

backward/grad run the eager tape (core.tape); PyLayer maps onto jax.custom_vjp
semantics but keeps the reference's class-based API
(``paddle/fluid/eager/pylayer/``).
"""

from ..core.tape import backward, grad, no_grad, enable_grad, set_grad_enabled
from .py_layer import PyLayer, PyLayerContext
from .functional import jvp, vjp, jacobian, hessian

__all__ = [
    "backward", "grad", "no_grad", "enable_grad", "set_grad_enabled",
    "PyLayer", "PyLayerContext", "jvp", "vjp", "jacobian", "hessian",
]
