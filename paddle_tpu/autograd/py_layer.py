"""PyLayer — user-defined forward/backward (reference:
``paddle/fluid/eager/pylayer/py_layer_node.h``, python ``paddle.autograd.PyLayer``).

The user's ``backward`` staticmethod becomes the tape node's vjp function
directly; saved tensors live on the context object, mirroring
``ctx.save_for_backward`` semantics.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core import tape as _tape
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Subclass and define ``forward(ctx, *args)`` and ``backward(ctx, *grads)``."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with _tape.no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        outs = out if isinstance(out, tuple) else (out,)

        in_tensors = [a for a in args if isinstance(a, Tensor)]
        needs_grad = _tape.is_grad_enabled() and any(
            not t.stop_gradient for t in in_tensors)
        if not needs_grad:
            return out

        out_avals = [
            jnp.zeros(o.shape, o.dtype) if isinstance(o, Tensor) else o
            for o in outs
        ]
        import jax
        out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o.dtype)
                     for o in outs if isinstance(o, Tensor)]

        def vjp_fn(cts):
            cts = cts if isinstance(cts, tuple) else (cts,)
            ct_tensors = tuple(Tensor(c) for c in cts)
            with _tape.no_grad():
                grads = cls.backward(ctx, *ct_tensors)
            grads = grads if isinstance(grads, tuple) else (grads,)
            out = []
            gi = iter(grads)
            for t in in_tensors:
                g = next(gi, None)
                out.append(None if g is None else
                           (g._value if isinstance(g, Tensor) else jnp.asarray(g)))
            return tuple(out)

        def vjp_tensor_fn(ct_tensors):
            # create_graph path: run the user backward with recording ON so
            # the ops inside it become tape nodes and the returned grads
            # are differentiable again
            with _tape.enable_grad():
                grads = cls.backward(ctx, *ct_tensors)
            grads = grads if isinstance(grads, tuple) else (grads,)
            out = []
            gi = iter(grads)
            for t in in_tensors:
                g = next(gi, None)
                out.append(None if g is None else
                           (g if isinstance(g, Tensor) else Tensor(jnp.asarray(g))))
            return tuple(out)

        node = _tape.TapeNode(cls.__name__, in_tensors, vjp_fn,
                              len(out_avals), out_avals,
                              vjp_tensor_fn=vjp_tensor_fn)
        wrapped = []
        slot = 0
        for o in outs:
            if isinstance(o, Tensor):
                t = Tensor(o._value, stop_gradient=False)
                t._node = node
                t._out_index = slot
                slot += 1
                wrapped.append(t)
            else:
                wrapped.append(o)
        return tuple(wrapped) if isinstance(out, tuple) else wrapped[0]
