"""Functional autodiff (analogue of paddle.incubate.autograd jvp/vjp/jacobian/
hessian, reference ``python/paddle/incubate/autograd/primapi.py``) — thin,
direct mappings onto jax transforms, which is the TPU-native design: the
reference needed a primitive-op system to get these; XLA gives them for free.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import tape as _tape


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    if isinstance(x, jax.Array):
        return Tensor(x)
    return x


def _functionalize(func):
    def pure(*arrays):
        with _tape.no_grad():
            out = func(*[Tensor(a) if isinstance(a, jax.Array) else a
                         for a in arrays])
        return _unwrap(out)

    return pure


def vjp(func, xs, v=None):
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_t]
    out, vjp_fn = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        v = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v = _unwrap(v)
    grads = vjp_fn(v)
    return _wrap(out), _wrap(list(grads) if len(grads) > 1 else grads[0])


def jvp(func, xs, v=None):
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_t]
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_t = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(_unwrap(t) for t in v_t)
    out, tangent_out = jax.jvp(_functionalize(func), tuple(arrays), tangents)
    return _wrap(out), _wrap(tangent_out)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_t]
    jac = jax.jacrev(_functionalize(func), argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        jac = jac[0]
    return _wrap(jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    xs_t = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs_t]
    hess = jax.hessian(_functionalize(func), argnums=tuple(range(len(arrays))))(*arrays)
    if not isinstance(xs, (list, tuple)):
        hess = hess[0][0]
    return _wrap(hess)
