"""paddle_tpu.fft — FFT family (≙ python/paddle/fft.py over pocketfft;
here XLA's native FFT, which lowers to the TPU's FFT implementation)."""

from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import dispatch
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
    "rfft2", "irfft2", "fftn", "ifftn", "rfftn", "irfftn", "fftfreq",
    "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    return None if norm == "backward" else norm


def _mk1d(jfn, name):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return dispatch(name, lambda a: jfn(a, n=n, axis=axis,
                                            norm=_norm(norm)), (x,))
    op.__name__ = name
    return op


fft = _mk1d(jnp.fft.fft, "fft")
ifft = _mk1d(jnp.fft.ifft, "ifft")
rfft = _mk1d(jnp.fft.rfft, "rfft")
irfft = _mk1d(jnp.fft.irfft, "irfft")
hfft = _mk1d(jnp.fft.hfft, "hfft")
ihfft = _mk1d(jnp.fft.ihfft, "ihfft")


def _mk2d(jfn, name):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_=None):
        return dispatch(name, lambda a: jfn(a, s=s, axes=axes,
                                            norm=_norm(norm)), (x,))
    op.__name__ = name
    return op


fft2 = _mk2d(jnp.fft.fft2, "fft2")
ifft2 = _mk2d(jnp.fft.ifft2, "ifft2")
rfft2 = _mk2d(jnp.fft.rfft2, "rfft2")
irfft2 = _mk2d(jnp.fft.irfft2, "irfft2")


def _mknd(jfn, name):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return dispatch(name, lambda a: jfn(a, s=s, axes=axes,
                                            norm=_norm(norm)), (x,))
    op.__name__ = name
    return op


fftn = _mknd(jnp.fft.fftn, "fftn")
ifftn = _mknd(jnp.fft.ifftn, "ifftn")
rfftn = _mknd(jnp.fft.rfftn, "rfftn")
irfftn = _mknd(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d))


def fftshift(x, axes=None, name=None):
    return dispatch("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes),
                    (x,))


def ifftshift(x, axes=None, name=None):
    return dispatch("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes),
                    (x,))
