"""Eager op dispatch.

TPU-native analogue of the reference's generated dygraph forward functions
(``eager_gen.py`` output: AMP cast -> API call -> GradNode wiring; see
SURVEY §3.1).  Every public op funnels through :func:`dispatch`:

    out = dispatch("matmul", impl_fn, (x, y), attrs)

- ``impl_fn`` is a pure function over jax arrays (closed over attrs).
- If grad is required, the op runs under ``jax.vjp`` and a TapeNode is
  recorded (the vjp closure *is* the grad node — XLA traces the transpose).
- AMP autocast happens here, mirroring eager_amp_auto_cast.h: ops are cast
  per-policy before the impl runs.
- NaN/Inf checking (FLAGS_check_nan_inf) mirrors eager/nan_inf_utils.cc.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import tape as _tape
from .flags import flag
from .tensor import Tensor
from ..runtime import HostTracer as _tracer

# AMP policy hook — set by paddle_tpu.amp at import; signature:
#   hook(op_name) -> target dtype to cast floating inputs to, or None.
# The cast happens INSIDE the differentiated function so cotangents flow
# back through convert_element_type into the original parameter dtype
# (master-weight-correct, unlike casting at the boundary).
_amp_cast_hook = None


def set_amp_cast_hook(fn):
    global _amp_cast_hook
    _amp_cast_hook = fn


# Parameter-access tracker: paddle_tpu.jit sets this to a dict {id: Parameter}
# during its discovery pass to learn which parameters a traced function reads
# (the analogue of to_static's program capture of persistable vars).
_param_tracker = None


def set_param_tracker(store):
    global _param_tracker
    _param_tracker = store


# Static-graph builder: paddle_tpu.static sets this under program_guard.
# Ops touching at least one symbolic Variable are recorded into the Program
# instead of executing (ops over concrete tensors still run eagerly — the
# analogue of the startup program running during build).
_static_builder = None


def set_static_builder(fn):
    global _static_builder
    _static_builder = fn


def _as_array(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (jax.Array, np.ndarray, int, float, bool, complex, np.generic)):
        return jnp.asarray(x)
    return jnp.asarray(x)


def _requires_grad(args) -> bool:
    if not _tape.is_grad_enabled():
        return False
    for a in args:
        if isinstance(a, Tensor) and not a.stop_gradient:
            return True
    return False


def _check_nan_inf(op_name, arrays):
    for i, a in enumerate(arrays):
        if isinstance(a, jax.core.Tracer):
            continue  # debug check is eager-only; no-op under jit tracing
        if jnp.issubdtype(a.dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(a)))
            if bad:
                raise FloatingPointError(
                    f"NaN/Inf detected in output {i} of op '{op_name}' "
                    "(FLAGS_check_nan_inf=1)")


def dispatch(op_name: str, impl: Callable, tensor_args: Sequence,
             nondiff_mask: Sequence[bool] = None,
             n_diff_outputs: int = None):
    """Execute ``impl(*arrays)`` eagerly with tape recording.

    tensor_args: positional tensor-like inputs of ``impl``.
    nondiff_mask: per-input True => never differentiate through that slot.
    n_diff_outputs: if impl returns a tuple, how many leading outputs are
      differentiable (the rest, e.g. argmax indices, are detached).
    """
    if _static_builder is not None and any(
            isinstance(a, Tensor) and hasattr(a, "_static_var_id")
            for a in tensor_args):
        return _static_builder(op_name, impl, tensor_args)
    if _param_tracker is not None:
        for a in tensor_args:
            if isinstance(a, Tensor) and a._is_param:
                _param_tracker.setdefault(id(a), a)
    if _tracer.enabled:  # ≙ RecordEvent instrumentation in operator.cc
        _tracer.begin(f"op::{op_name}")
        try:
            return _dispatch_impl(op_name, impl, tensor_args, nondiff_mask,
                                  n_diff_outputs)
        finally:
            _tracer.end()
    return _dispatch_impl(op_name, impl, tensor_args, nondiff_mask,
                          n_diff_outputs)


def _dispatch_impl(op_name, impl, tensor_args, nondiff_mask, n_diff_outputs):
    arrays = [_as_array(a) for a in tensor_args]
    if _amp_cast_hook is not None:
        cast_dtype = _amp_cast_hook(op_name)
        if cast_dtype is not None:
            inner_impl = impl

            def impl(*full, _inner=inner_impl, _d=cast_dtype):
                cast = [
                    a.astype(_d)
                    if (jnp.issubdtype(a.dtype, jnp.floating) and a.dtype != _d)
                    else a
                    for a in full
                ]
                return _inner(*cast)

    needs_grad = _requires_grad(tensor_args)
    if needs_grad and nondiff_mask is not None:
        needs_grad = any(
            isinstance(a, Tensor) and not a.stop_gradient and not nd
            for a, nd in zip(tensor_args, nondiff_mask))

    if not needs_grad:
        out = impl(*arrays)
        outs = out if isinstance(out, tuple) else (out,)
        if flag("check_nan_inf"):
            _check_nan_inf(op_name, outs)
        wrapped = tuple(Tensor(o, stop_gradient=True) for o in outs)
        return wrapped if isinstance(out, tuple) else wrapped[0]

    # split diff vs nondiff inputs so vjp only tracks the diff ones
    if nondiff_mask is None:
        nondiff_mask = [False] * len(arrays)
    diff_idx = [i for i, nd in enumerate(nondiff_mask) if not nd]
    fixed = {i: arrays[i] for i, nd in enumerate(nondiff_mask) if nd}

    def f(*diff_arrays):
        full = list(arrays)
        for j, i in enumerate(diff_idx):
            full[i] = diff_arrays[j]
        for i, v in fixed.items():
            full[i] = v
        return impl(*full)

    out, vjp_fn = jax.vjp(f, *[arrays[i] for i in diff_idx])
    outs = out if isinstance(out, tuple) else (out,)
    if flag("check_nan_inf"):
        _check_nan_inf(op_name, outs)

    in_tensors = []
    for i in diff_idx:
        a = tensor_args[i]
        in_tensors.append(a if isinstance(a, Tensor) else Tensor(arrays[i]))

    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs]
    node = _tape.TapeNode(op_name, in_tensors, vjp_fn, len(outs), out_avals,
                          out_is_tuple=isinstance(out, tuple), f=f)

    if n_diff_outputs is None:
        n_diff_outputs = len(outs)
    wrapped = []
    for slot, o in enumerate(outs):
        diff = slot < n_diff_outputs and jnp.issubdtype(o.dtype, jnp.inexact)
        t = Tensor(o, stop_gradient=not diff)
        if diff:
            t._node = node
            t._out_index = slot
        wrapped.append(t)
    return tuple(wrapped) if isinstance(out, tuple) else wrapped[0]
