"""Eager autograd tape.

TPU-native analogue of the reference's eager autograd graph
(``paddle/fluid/eager/grad_node_info.h:168`` GradNodeBase/Edge,
``paddle/fluid/eager/backward.cc:104`` RunBackward): every differentiable
eager op records a ``TapeNode`` holding a ``jax.vjp`` closure.  ``backward``
walks the node graph in reverse with in-degree bookkeeping (the same
ready-queue scheme as the reference's RunBackward hot loop) and accumulates
cotangents into leaf ``.grad``.

Design notes (why this is TPU-idiomatic rather than a port):
- Instead of per-op handwritten GradNode classes generated from YAML, each
  node's backward is the XLA-traced transpose produced by ``jax.vjp``; when a
  node wraps a ``jax.jit``-ed function (the to_static path), its backward is a
  single compiled program — the analogue of RunProgramGradNode
  (``paddle/fluid/eager/to_static/run_program_op_node.h:314``).
- Gradient accumulation is jnp addition (fused by XLA), not GradTensorHolder.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_state = threading.local()


def _tls():
    if not hasattr(_state, "grad_enabled"):
        _state.grad_enabled = True
    return _state


def is_grad_enabled() -> bool:
    return _tls().grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tls().grad_enabled = bool(mode)


class no_grad:
    """Context manager + decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def set_grad_enabled_ctx(mode: bool):
    return enable_grad() if mode else no_grad()


class TapeNode:
    """One recorded op: inputs (Tensors), a vjp closure, and output slots."""

    __slots__ = (
        "op_name", "inputs", "vjp_fn", "n_outputs", "out_avals",
        "out_is_tuple", "_out_cotangents", "_pending", "released",
    )

    def __init__(self, op_name: str, inputs: Sequence[Any], vjp_fn: Callable,
                 n_outputs: int, out_avals: List[Any],
                 out_is_tuple: bool = False):
        self.op_name = op_name
        self.inputs = list(inputs)          # input Tensors (strong refs)
        self.vjp_fn = vjp_fn
        self.n_outputs = n_outputs
        self.out_avals = out_avals          # ShapeDtypeStruct per output
        self.out_is_tuple = out_is_tuple    # primal returned a tuple pytree
        self._out_cotangents = None
        self._pending = 0
        self.released = False

    def release(self):
        self.vjp_fn = None
        self.inputs = []
        self.released = True


def _zero_cotangent(aval):
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def backward(root_tensors: Sequence[Any],
             grad_tensors: Optional[Sequence[Any]] = None,
             retain_graph: bool = False) -> None:
    """Run reverse accumulation from ``root_tensors`` into leaf ``.grad``."""
    _run_backward(root_tensors, grad_tensors, retain_graph,
                  inputs=None, accumulate_into_grad=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """``paddle.grad`` analogue: return grads of ``outputs`` w.r.t ``inputs``.

    create_graph is currently unsupported in the eager tape (use the
    functional API / :func:`paddle_tpu.incubate.autograd` for higher-order).
    """
    if create_graph:
        raise NotImplementedError(
            "create_graph=True is not supported by the eager tape; "
            "use the functional jax.grad path (paddle_tpu.jit) instead")
    grads = _run_backward(outputs, grad_outputs, retain_graph,
                          inputs=list(inputs), accumulate_into_grad=False)
    out = []
    for t, g in zip(inputs, grads):
        if g is None and not allow_unused:
            raise ValueError(
                f"one of the differentiated tensors ({t.name}) appears unused; "
                "pass allow_unused=True to return None for it")
        out.append(g)
    return out


def _run_backward(root_tensors, grad_tensors, retain_graph, inputs,
                  accumulate_into_grad):
    from .tensor import Tensor  # cycle-free at call time

    roots = [root_tensors] if isinstance(root_tensors, Tensor) else list(root_tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # ---- discover reachable subgraph & count consumers (in-degrees) ----
    nodes = {}
    stack = []
    for t in roots:
        if t._node is not None and not t._node.released:
            stack.append(t._node)
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        node._pending = 0
        node._out_cotangents = [None] * node.n_outputs
        for inp in node.inputs:
            pnode = inp._node
            if pnode is not None and not pnode.released:
                stack.append(pnode)
    for node in nodes.values():
        for inp in node.inputs:
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                pnode._pending += 1

    # grads accumulated per *tensor* (keyed by id of its data slot)
    tensor_grads = {}

    def _accum_tensor_grad(t, g):
        if g is None or _is_float0(g):
            return
        key = id(t)
        prev = tensor_grads.get(key)
        tensor_grads[key] = (t, g if prev is None else prev[1] + g)

    # ---- seed roots ----
    for t, g in zip(roots, grad_tensors):
        if g is None:
            if t.size != 1:
                raise ValueError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape={t.shape})")
            gval = jnp.ones(t._value.shape, t._value.dtype)
        else:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._node
        if node is not None and id(node) in nodes:
            slot = t._out_index
            prev = node._out_cotangents[slot]
            node._out_cotangents[slot] = gval if prev is None else prev + gval
        _accum_tensor_grad(t, gval)

    # ---- ready-queue traversal (reference: backward.cc:104 RunBackward) ----
    ready = [n for n in nodes.values() if n._pending == 0]
    processed = set()
    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))

        cts = [
            ct if ct is not None else _zero_cotangent(aval)
            for ct, aval in zip(node._out_cotangents, node.out_avals)
        ]
        in_cts = node.vjp_fn(tuple(cts) if node.out_is_tuple else cts[0])
        node._out_cotangents = None

        node_inputs = node.inputs
        for inp, g in zip(node_inputs, in_cts):
            if inp.stop_gradient or g is None or _is_float0(g):
                continue
            # tensor-level hooks fire on the produced cotangent
            for hook in inp._grad_hooks:
                new_g = hook(inp._wrap_grad(g))
                if new_g is not None:
                    g = new_g._value if isinstance(new_g, Tensor) else jnp.asarray(new_g)
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                slot = inp._out_index
                prev = pnode._out_cotangents[slot]
                pnode._out_cotangents[slot] = g if prev is None else prev + g
            _accum_tensor_grad(inp, g)

        # countdown producers, then free this node's residuals
        for inp in node_inputs:
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                pnode._pending -= 1
                if pnode._pending == 0:
                    ready.append(pnode)
        if not retain_graph:
            node.release()

    if accumulate_into_grad:
        for t, g in tensor_grads.values():
            if t.stop_gradient or not t.is_leaf:
                continue
            t._accumulate_grad(g)
        return None
    else:
        out = []
        for t in inputs:
            entry = tensor_grads.get(id(t))
            out.append(None if entry is None else t._wrap_grad(entry[1]))
        return out
