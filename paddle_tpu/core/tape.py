"""Eager autograd tape.

TPU-native analogue of the reference's eager autograd graph
(``paddle/fluid/eager/grad_node_info.h:168`` GradNodeBase/Edge,
``paddle/fluid/eager/backward.cc:104`` RunBackward): every differentiable
eager op records a ``TapeNode`` holding a ``jax.vjp`` closure.  ``backward``
walks the node graph in reverse with in-degree bookkeeping (the same
ready-queue scheme as the reference's RunBackward hot loop) and accumulates
cotangents into leaf ``.grad``.

Design notes (why this is TPU-idiomatic rather than a port):
- Instead of per-op handwritten GradNode classes generated from YAML, each
  node's backward is the XLA-traced transpose produced by ``jax.vjp``; when a
  node wraps a ``jax.jit``-ed function (the to_static path), its backward is a
  single compiled program — the analogue of RunProgramGradNode
  (``paddle/fluid/eager/to_static/run_program_op_node.h:314``).
- Gradient accumulation is jnp addition (fused by XLA), not GradTensorHolder.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_state = threading.local()


def _tls():
    if not hasattr(_state, "grad_enabled"):
        _state.grad_enabled = True
    return _state


def is_grad_enabled() -> bool:
    return _tls().grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tls().grad_enabled = bool(mode)


class no_grad:
    """Context manager + decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = is_grad_enabled()
        set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        set_grad_enabled(self._prev)
        return False


def set_grad_enabled_ctx(mode: bool):
    return enable_grad() if mode else no_grad()


class TapeNode:
    """One recorded op: inputs (Tensors), a vjp closure, and output slots.

    ``f`` is the forward closure over the diff input arrays — kept so a
    ``create_graph=True`` backward can RE-dispatch the vjp as a recorded
    op (re-linearized via ``jax.vjp(f, ...)``), making the cotangent
    computation itself differentiable (reference:
    ``paddle.grad(..., create_graph=True)``,
    ``python/paddle/base/dygraph/base.py:600``).
    """

    __slots__ = (
        "op_name", "inputs", "vjp_fn", "n_outputs", "out_avals",
        "out_is_tuple", "f", "vjp_tensor_fn", "_out_cotangents", "_pending",
        "released",
    )

    def __init__(self, op_name: str, inputs: Sequence[Any], vjp_fn: Callable,
                 n_outputs: int, out_avals: List[Any],
                 out_is_tuple: bool = False, f: Callable = None,
                 vjp_tensor_fn: Callable = None):
        self.op_name = op_name
        self.inputs = list(inputs)          # input Tensors (strong refs)
        self.vjp_fn = vjp_fn
        self.n_outputs = n_outputs
        self.out_avals = out_avals          # ShapeDtypeStruct per output
        self.out_is_tuple = out_is_tuple    # primal returned a tuple pytree
        self.f = f                          # forward closure (diff args)
        # create_graph alternative for nodes without a re-traceable f
        # (PyLayer): takes Tensor cotangents, runs the user backward with
        # recording ON, returns Tensor grads
        self.vjp_tensor_fn = vjp_tensor_fn
        self._out_cotangents = None
        self._pending = 0
        self.released = False

    def release(self):
        self.vjp_fn = None
        self.inputs = []
        self.f = None
        self.vjp_tensor_fn = None
        self.released = True


def _zero_cotangent(aval):
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


def _vjp_as_recorded_op(node, cts):
    """create_graph backward step: evaluate the node's vjp as a DISPATCHED
    op (re-linearized with jax.vjp from the stored forward closure), so the
    produced cotangents carry their own tape nodes and are differentiable.
    Returns a tuple of Tensors aligned with node.inputs."""
    from .dispatch import dispatch
    from .tensor import Tensor

    n_out = node.n_outputs
    f, out_is_tuple = node.f, node.out_is_tuple
    # float0 cotangents (non-inexact outputs) stay fixed closure-side
    var_idx = [i for i in range(n_out)
               if not _is_float0(cts[i] if not isinstance(cts[i], Tensor)
                                 else cts[i]._value)]
    fixed = {i: cts[i] for i in range(n_out) if i not in set(var_idx)}
    var_cts = [cts[i] if isinstance(cts[i], Tensor) else Tensor(cts[i])
               for i in var_idx]
    n_var = len(var_cts)

    def impl(*arrays):
        ct_arrays = arrays[:n_var]
        prim = arrays[n_var:]
        full, vi = [], iter(ct_arrays)
        for i in range(n_out):
            full.append(fixed[i] if i in fixed else next(vi))
        _, vjp_fn = jax.vjp(f, *prim)
        res = vjp_fn(tuple(full) if out_is_tuple else full[0])
        return tuple(res) if len(res) > 1 else res[0]

    with enable_grad():
        out = dispatch("grad::" + node.op_name, impl,
                       tuple(var_cts) + tuple(node.inputs))
    return out if isinstance(out, tuple) else (out,)


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def backward(root_tensors: Sequence[Any],
             grad_tensors: Optional[Sequence[Any]] = None,
             retain_graph: bool = False) -> None:
    """Run reverse accumulation from ``root_tensors`` into leaf ``.grad``."""
    _run_backward(root_tensors, grad_tensors, retain_graph,
                  inputs=None, accumulate_into_grad=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=False,
         create_graph=False, allow_unused=False):
    """``paddle.grad`` analogue: return grads of ``outputs`` w.r.t ``inputs``.

    With ``create_graph=True`` every vjp evaluation is itself dispatched
    as a recorded op, so the returned grads carry tape nodes and can be
    differentiated again (gradient-penalty training, grad-of-grad).
    """
    grads = _run_backward(outputs, grad_outputs,
                          retain_graph or create_graph,
                          inputs=list(inputs), accumulate_into_grad=False,
                          create_graph=create_graph)
    out = []
    for t, g in zip(inputs, grads):
        if g is None and not allow_unused:
            raise ValueError(
                f"one of the differentiated tensors ({t.name}) appears unused; "
                "pass allow_unused=True to return None for it")
        out.append(g)
    return out


def _run_backward(root_tensors, grad_tensors, retain_graph, inputs,
                  accumulate_into_grad, create_graph=False):
    from .tensor import Tensor  # cycle-free at call time

    roots = [root_tensors] if isinstance(root_tensors, Tensor) else list(root_tensors)
    if grad_tensors is None:
        grad_tensors = [None] * len(roots)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # ---- discover reachable subgraph & count consumers (in-degrees) ----
    nodes = {}
    stack = []
    for t in roots:
        if t._node is not None and not t._node.released:
            stack.append(t._node)
    while stack:
        node = stack.pop()
        if id(node) in nodes:
            continue
        nodes[id(node)] = node
        node._pending = 0
        node._out_cotangents = [None] * node.n_outputs
        for inp in node.inputs:
            pnode = inp._node
            if pnode is not None and not pnode.released:
                stack.append(pnode)
    for node in nodes.values():
        for inp in node.inputs:
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                pnode._pending += 1

    # grads accumulated per *tensor* (keyed by id of its data slot)
    tensor_grads = {}

    def _accum_tensor_grad(t, g):
        if g is None or _is_float0(g):
            return
        key = id(t)
        prev = tensor_grads.get(key)
        tensor_grads[key] = (t, g if prev is None else prev[1] + g)

    # ---- seed roots ----
    for t, g in zip(roots, grad_tensors):
        if g is None:
            if t.size != 1:
                raise ValueError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape={t.shape})")
            gval = jnp.ones(t._value.shape, t._value.dtype)
        else:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            gval = g if isinstance(g, Tensor) else Tensor(gval)
        node = t._node
        if node is not None and id(node) in nodes:
            slot = t._out_index
            prev = node._out_cotangents[slot]
            node._out_cotangents[slot] = gval if prev is None else prev + gval
        _accum_tensor_grad(t, gval)

    # ---- ready-queue traversal (reference: backward.cc:104 RunBackward) ----
    ready = [n for n in nodes.values() if n._pending == 0]
    processed = set()
    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))

        cts = [
            ct if ct is not None else _zero_cotangent(aval)
            for ct, aval in zip(node._out_cotangents, node.out_avals)
        ]
        if create_graph and node.f is not None:
            in_cts = _vjp_as_recorded_op(node, cts)
        elif create_graph and node.vjp_tensor_fn is not None:
            ct_tensors = [c if isinstance(c, Tensor) else
                          (c if _is_float0(c) else Tensor(c)) for c in cts]
            in_cts = node.vjp_tensor_fn(ct_tensors)
        elif create_graph:
            raise NotImplementedError(
                f"create_graph=True cannot differentiate through op "
                f"'{node.op_name}': its backward is an opaque closure "
                "(no re-traceable forward). Rebuild the graph with "
                "dispatch-recorded ops or a PyLayer.")
        else:
            raw = [c._value if isinstance(c, Tensor) else c for c in cts]
            in_cts = node.vjp_fn(tuple(raw) if node.out_is_tuple else raw[0])
        node._out_cotangents = None

        node_inputs = node.inputs
        for inp, g in zip(node_inputs, in_cts):
            if inp.stop_gradient or g is None or _is_float0(g):
                continue
            # tensor-level hooks fire on the produced cotangent
            for hook in inp._grad_hooks:
                new_g = hook(g if isinstance(g, Tensor) else
                             inp._wrap_grad(g))
                if new_g is not None:
                    g = new_g if create_graph and isinstance(new_g, Tensor) \
                        else (new_g._value if isinstance(new_g, Tensor)
                              else jnp.asarray(new_g))
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                slot = inp._out_index
                prev = pnode._out_cotangents[slot]
                pnode._out_cotangents[slot] = g if prev is None else prev + g
            _accum_tensor_grad(inp, g)

        # countdown producers, then free this node's residuals
        for inp in node_inputs:
            pnode = inp._node
            if pnode is not None and id(pnode) in nodes:
                pnode._pending -= 1
                if pnode._pending == 0:
                    ready.append(pnode)
        if not retain_graph:
            node.release()

    if accumulate_into_grad:
        for t, g in tensor_grads.values():
            if t.stop_gradient or not t.is_leaf:
                continue
            t._accumulate_grad(g)
        return None
    else:
        out = []
        for t in inputs:
            entry = tensor_grads.get(id(t))
            if entry is None:
                out.append(None)
            elif isinstance(entry[1], Tensor):
                out.append(entry[1])       # create_graph: keeps its node
            else:
                out.append(t._wrap_grad(entry[1]))
        return out
