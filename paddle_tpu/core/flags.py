"""Global flag registry.

TPU-native analogue of the reference's gflags-style system
(``paddle/phi/core/flags.h:46-90``, surfaced in Python as
``paddle.set_flags/get_flags`` and ``FLAGS_*`` env vars).  Flags are plain
Python values; env vars named ``FLAGS_<name>`` override defaults at import.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Iterable, Union

from .errors import NotFoundError

_lock = threading.Lock()
_FLAGS: Dict[str, Any] = {}
_DEFS: Dict[str, dict] = {}


def _coerce(value: str, default: Any):
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(value)
    if isinstance(default, float):
        return float(value)
    return value


def define_flag(name: str, default: Any, help_str: str = "") -> None:
    """Register a flag (``PHI_DEFINE_EXPORTED_*`` analogue)."""
    with _lock:
        _DEFS[name] = {"default": default, "help": help_str}
        env = os.environ.get("FLAGS_" + name)
        _FLAGS[name] = _coerce(env, default) if env is not None else default


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flag values; mirrors ``paddle.set_flags``."""
    with _lock:
        for k, v in flags.items():
            key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
            if key not in _FLAGS:
                raise NotFoundError(f"unknown flag {k!r}")
            _FLAGS[key] = v


def get_flags(flags: Union[str, Iterable[str]]) -> Dict[str, Any]:
    """Get flag values; mirrors ``paddle.get_flags``."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    with _lock:
        for k in flags:
            key = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
            if key not in _FLAGS:
                raise NotFoundError(f"unknown flag {k!r}")
            out["FLAGS_" + key] = _FLAGS[key]
    return out


def flag(name: str) -> Any:
    """Fast internal accessor."""
    return _FLAGS[name]


# Core flags (subset of paddle/phi/core/flags.cc that is meaningful on TPU).
define_flag("check_nan_inf", False, "scan op outputs for NaN/Inf in eager mode")
define_flag("benchmark", False, "block on every op for accurate eager timing")
define_flag("use_autotune", True, "enable pallas kernel autotuning cache")
define_flag("adamw_rsqrt_update", False,
            "Adam/AdamW update via m_hat * rsqrt(v_hat + eps^2) — the "
            "original Adam paper's epsilon-hat variant — instead of "
            "m_hat / (sqrt(v_hat) + eps); hardware rsqrt avoids the VPU "
            "divide+sqrt stall (25% faster update sweep on v5e)")
define_flag("flash_onepass_bwd", True,
            "flash-attention backward as one dq+dk+dv kernel (softmax "
            "weights rebuilt once per block pair instead of once per "
            "pass) — disable to fall back to the two-pass dq/dkv form")
define_flag("use_fused_adamw_kernel", False,
            "route single-chip AdamW update sweeps through the Pallas "
            "fused kernel. Opt-in: measured only ~12 ms/step faster than "
            "XLA's update fusions at 0.62B params on v5e, while costing "
            "~520 MB of HBM headroom (layout-conversion copies around "
            "the custom call)")
define_flag("use_decode_attention_kernel", True,
            "fused flash-decode attention kernel for cached decode "
            "(one pass over the cache, prefix-aware streaming — slots "
            "beyond the valid length are never read); disable to fall "
            "back to the XLA einsum attention")
define_flag("use_int8_matmul_kernel", False,
            "route int8-weight linears through the Pallas quantized matmul "
            "(measured at parity with the XLA dequant+matmul on v5; opt-in)")
define_flag("eager_log_level", 0, "verbosity of eager dispatch logging")
define_flag("low_precision_op_list", 0, "record ops executed under AMP")
define_flag("default_dtype", "float32", "default floating point dtype")
define_flag("prefer_pallas_kernels", True,
            "use pallas kernels for flash-attention/norms on TPU backends")
define_flag("allocator_strategy", "auto_growth",
            "accepted for API parity; XLA owns device memory on TPU")
