"""Typed, rich error machinery.

TPU-native analogue of the reference's enforce/error system
(``paddle/phi/core/enforce.h``, ``paddle/phi/core/errors.h``): a family of
typed exceptions plus ``enforce``-style check helpers that build readable
messages with context.  We raise ordinary Python exceptions (no C++ stack
capture is needed — Python tracebacks already provide it).
"""

from __future__ import annotations


class PaddleTpuError(Exception):
    """Base class for framework errors."""

    code = "Error"

    def __init__(self, message: str = ""):
        super().__init__(f"[{self.code}] {message}" if message else self.code)
        self.message = message


class InvalidArgumentError(PaddleTpuError, ValueError):
    code = "InvalidArgument"


class NotFoundError(PaddleTpuError, KeyError):
    code = "NotFound"


class OutOfRangeError(PaddleTpuError, IndexError):
    code = "OutOfRange"


class AlreadyExistsError(PaddleTpuError):
    code = "AlreadyExists"


class PermissionDeniedError(PaddleTpuError):
    code = "PermissionDenied"


class UnimplementedError(PaddleTpuError, NotImplementedError):
    code = "Unimplemented"


class UnavailableError(PaddleTpuError, RuntimeError):
    code = "Unavailable"


class PreconditionNotMetError(PaddleTpuError, RuntimeError):
    code = "PreconditionNotMet"


class ExecutionTimeoutError(PaddleTpuError, TimeoutError):
    code = "ExecutionTimeout"


class FatalError(PaddleTpuError, RuntimeError):
    code = "Fatal"


def enforce(cond, message: str = "", exc=InvalidArgumentError):
    """``PADDLE_ENFORCE`` analogue: raise ``exc`` with ``message`` if not cond."""
    if not cond:
        raise exc(message)


def enforce_eq(a, b, message: str = ""):
    if a != b:
        raise InvalidArgumentError(f"expected {a!r} == {b!r}. {message}")


def enforce_not_none(value, name: str = "value"):
    if value is None:
        raise InvalidArgumentError(f"{name} must not be None")
    return value


def enforce_shape_rank(shape, rank: int, name: str = "input"):
    if len(shape) != rank:
        raise InvalidArgumentError(
            f"{name} expected rank {rank}, got rank {len(shape)} (shape={list(shape)})"
        )
