"""Device management.

TPU-native analogue of ``paddle.device`` (reference:
``python/paddle/device/__init__.py:244 set_device``) and the backend/device
registry (``paddle/phi/backends/device_manager.h:134``).  On JAX, devices are
enumerated by the runtime (PJRT); "places" become thin descriptors wrapping a
``jax.Device``.  The PJRT plugin mechanism is the analogue of the reference's
custom-device C API (``paddle/phi/backends/device_ext.h:94``): third-party
hardware integrates below us, so no extra plugin layer is re-implemented here.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax


class Place:
    """A device descriptor (analogue of ``phi::Place``)."""

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = device_id

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_tpu_place(self):
        return self.device_type in ("tpu", "axon")

    def jax_device(self) -> Optional[jax.Device]:
        devs = [d for d in jax.devices() if _devtype(d) == self.device_type]
        if not devs:
            devs = jax.devices()
        return devs[min(self.device_id, len(devs) - 1)]


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(device_id: int = 0):
    return Place("tpu", device_id)


def _devtype(d: jax.Device) -> str:
    plat = d.platform
    return "tpu" if plat == "axon" else plat


_current_place: Optional[Place] = None


@functools.lru_cache(maxsize=None)
def _default_backend() -> str:
    return _devtype(jax.devices()[0])


def get_all_device_type():
    return sorted({_devtype(d) for d in jax.devices()})


def device_count(device_type: Optional[str] = None) -> int:
    if device_type is None:
        return jax.device_count()
    return len([d for d in jax.devices() if _devtype(d) == device_type])


def set_device(device: str) -> Place:
    """Mirror ``paddle.set_device``; accepts 'cpu', 'tpu', 'tpu:0'."""
    global _current_place
    if ":" in device:
        dtype_, idx = device.split(":", 1)
        place = Place(dtype_, int(idx))
    else:
        place = Place(device, 0)
    _current_place = place
    return place


def get_device() -> str:
    place = current_place()
    return f"{place.device_type}:{place.device_id}"


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = Place(_default_backend(), 0)
    return _current_place


def CUDAPlace(dev_id: int = 0) -> Place:
    """API-parity constructor: in this TPU build "cuda" names the
    accelerator, so CUDAPlace maps to the TPU place (the cuda shim in
    paddle_tpu.device does the same for device strings)."""
    return Place("tpu", dev_id)


def is_compiled_with_cuda() -> bool:  # API parity: this build has no CUDA
    return False


def is_compiled_with_tpu() -> bool:
    return any(_devtype(d) == "tpu" for d in jax.devices())


def is_tpu_backend() -> bool:
    return _default_backend() == "tpu"


def synchronize():
    """Block until all dispatched device work completes."""
    (jax.device_put(0.0) + 0).block_until_ready()


class Stream:
    """Stream facade (≙ paddle.device.Stream / cuda streams).

    XLA owns stream scheduling on TPU — compiled programs already overlap
    compute, HBM traffic and collectives — so a Stream here is an ordering
    scope: ``synchronize`` drains the device; ``record_event``/``wait_event``
    give the reference's event-ordering API over block_until_ready.
    """

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize()

    def record_event(self, event: "Event" = None) -> "Event":
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event: "Event"):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        stream.synchronize()


class Event:
    """Event facade (≙ paddle.device.Event): records a point in the
    dispatched work; query/synchronize/elapsed_time over host clocks after a
    device drain."""

    def __init__(self, enable_timing: bool = True, blocking: bool = False):
        self.enable_timing = enable_timing
        self._time_ns = None

    def record(self, stream: Optional[Stream] = None):
        from ..runtime import now_ns
        synchronize()  # device-complete timestamp
        self._time_ns = now_ns()

    def query(self) -> bool:
        return self._time_ns is not None

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event: "Event") -> float:
        """Milliseconds between two recorded events."""
        if self._time_ns is None or end_event._time_ns is None:
            raise RuntimeError("both events must be recorded")
        return (end_event._time_ns - self._time_ns) / 1e6


_default_stream = Stream()


def current_stream(device=None) -> Stream:
    return _default_stream


def stream_guard(stream: Stream):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield stream

    return guard()


def memory_stats(device: Optional[str] = None) -> dict:
    """Device memory statistics: HBM numbers from PJRT plus host-runtime
    counters (≙ paddle/fluid/memory/stats.h surfaced via paddle.device)."""
    from .. import runtime as rt
    if device is None:
        place = current_place()
    elif ":" in device:  # a query must not mutate the current device
        dtype_, idx = device.split(":", 1)
        place = Place(dtype_, int(idx))
    else:
        place = Place(device, 0)
    stats = {}
    try:
        dev_stats = place.jax_device().memory_stats() or {}
        stats.update(dev_stats)
    except Exception:
        pass
    for name in rt.stat_names():
        stats[f"host.{name}"] = rt.stat_current(name)
    return stats


def max_memory_allocated(device=None) -> int:
    s = memory_stats(device)
    return int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def empty_cache():
    """No-op on XLA (allocator is runtime-managed); kept for API parity."""
