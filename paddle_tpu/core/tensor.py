"""The eager Tensor facade.

TPU-native analogue of ``paddle::Tensor`` + dygraph autograd meta
(reference: ``paddle/phi/api/include/tensor.h``,
``paddle/fluid/eager/autograd_meta.h:61``).  A ``Tensor`` wraps an immutable
``jax.Array`` plus mutable framework state: ``stop_gradient``, ``.grad``,
tape linkage, hooks, and a name.  In-place ops swap the wrapped array (XLA
arrays are immutable; mutation is a facade — the TPU-correct design).

The ``__jax_array__`` protocol makes Tensors directly consumable by any
``jax.numpy`` function, which keeps interop and testing friction-free.
"""

from __future__ import annotations

import itertools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtypes as _dtypes
from . import tape as _tape
from .device import current_place

_name_counter = itertools.count()


def _auto_name(prefix="tensor"):
    return f"{prefix}_{next(_name_counter)}"


class Tensor:
    __slots__ = (
        "_value", "stop_gradient", "_grad", "_node", "_out_index",
        "_grad_hooks", "name", "persistable", "_is_param", "_dist_attr",
        "_static_var_id",  # set only on static-graph Variables (static mode)
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value if isinstance(value, jax.Array) else jnp.asarray(value)
        self.stop_gradient = stop_gradient
        self._grad = None
        self._node = None          # producing TapeNode (None => leaf)
        self._out_index = 0        # output slot in the producing node
        self._grad_hooks = []
        self.name = name or _auto_name()
        self.persistable = False
        self._is_param = False
        self._dist_attr = None     # sharding annotation (PartitionSpec) if any

    # ---- array protocol interop ----
    def __jax_array__(self):
        return self._value

    def __array__(self, dtype=None):
        arr = np.asarray(self._value)
        return arr.astype(dtype) if dtype is not None else arr

    # ---- meta ----
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        return current_place()

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def T(self):
        from .. import tensor as ops
        return ops.transpose(self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from .. import tensor as ops
        perm = list(range(self.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return ops.transpose(self, perm)

    def numel(self):
        return self.size

    # ---- conversions ----
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        return np.asarray(self._value).item(*args)

    def tolist(self):
        return np.asarray(self._value).tolist()

    def astype(self, dtype):
        from .dispatch import dispatch
        d = _dtypes.convert_dtype(dtype)
        return dispatch("cast", lambda x: x.astype(d), (self,))

    cast = astype

    def clone(self):
        from .dispatch import dispatch
        return dispatch("clone", lambda x: x + jnp.zeros((), x.dtype), (self,))

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def cpu(self):
        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def cuda(self, device_id=None, blocking=True):
        """API parity: move to the accelerator (TPU in this build)."""
        devs = jax.devices()
        idx = 0 if device_id is None else int(device_id)
        if not 0 <= idx < len(devs):
            raise ValueError(
                f"device_id {device_id} out of range (have {len(devs)} "
                "device(s))")
        return Tensor(jax.device_put(self._value, devs[idx]),
                      stop_gradient=self.stop_gradient)

    def pin_memory(self):
        """API parity: host staging buffers are managed by the runtime's
        double-buffered transfers; returns self."""
        return self

    def element_size(self):
        return int(jnp.dtype(self._value.dtype).itemsize)

    def to(self, *args, **kwargs):
        # accepts dtype or device strings like the reference's Tensor.to
        out = self
        for a in args:
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu"):
                continue  # single logical device space under jit
            out = out.astype(a)
        if "dtype" in kwargs and kwargs["dtype"] is not None:
            out = out.astype(kwargs["dtype"])
        return out

    # ---- autograd ----
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad = None
        else:
            self._grad = value if isinstance(value, Tensor) else Tensor(value)

    def _wrap_grad(self, arr):
        return Tensor(arr, stop_gradient=True, name=self.name + "@GRAD")

    def _accumulate_grad(self, arr):
        if self._grad is None:
            self._grad = self._wrap_grad(arr)
        else:
            self._grad = self._wrap_grad(self._grad._value + arr)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        _tape.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Register a gradient hook (reference: eager/hooks.h TensorHook)."""
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(_self):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass

        return _Handle()

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        self._node = None
        return self

    def set_value(self, value):
        value = value._value if isinstance(value, Tensor) else jnp.asarray(value)
        self._value = value.astype(self._value.dtype) if value.dtype != self._value.dtype else value
        self._node = None
        return self

    def copy_(self, other):
        return self.set_value(other)

    def _in_place_update(self, new_tensor: "Tensor"):
        """Adopt another tensor's value+tape linkage (in-place op facade)."""
        self._value = new_tensor._value
        self._node = new_tensor._node
        self._out_index = new_tensor._out_index
        self.stop_gradient = new_tensor.stop_gradient
        if self._node is not None:
            # re-point the node's recorded output tensor to self is not needed:
            # nodes reference inputs only; outputs are tracked via (_node,_out_index)
            pass
        return self

    # ---- python protocol ----
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __bool__(self):
        import jax
        if isinstance(self._value, jax.core.Tracer):
            raise TypeError(
                "bool() on a traced Tensor: Python control flow over "
                "tensor values inside a compiled region needs dy2static "
                "conversion — decorate the function with "
                "paddle.jit.to_static (its source must be available; "
                "REPL/stdin-defined functions cannot be converted) or use "
                "paddle.static.nn.cond/while_loop explicitly.")
        return bool(np.asarray(self._value))

    def __int__(self):
        return int(np.asarray(self._value))

    def __float__(self):
        return float(np.asarray(self._value))

    def __index__(self):
        return int(np.asarray(self._value))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={_dtypes.dtype_name(self.dtype)}"
                f"{grad_info},\n       {np.asarray(self._value)})")

    def __dlpack__(self, *a, **k):
        return self._value.__dlpack__(*a, **k)

    def __dlpack_device__(self):
        return self._value.__dlpack_device__()

    # Arithmetic/indexing methods are patched in by paddle_tpu.tensor at import
    # (the analogue of python/paddle/base/dygraph/tensor_patch_methods.py).


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """Mirror ``paddle.to_tensor``."""
    if isinstance(data, Tensor):
        out = data.astype(dtype) if dtype is not None else data.clone()
        out.stop_gradient = stop_gradient
        return out
    d = _dtypes.convert_dtype(dtype)
    if d is None and not hasattr(data, "dtype"):
        # python scalars/lists: match the reference's defaulting rules
        probe = np.asarray(data)
        if probe.dtype == np.float64:
            d = _dtypes.default_float_dtype()
        elif probe.dtype == np.int64:
            d = _dtypes.int64
    arr = jnp.asarray(data, dtype=d)
    return Tensor(arr, stop_gradient=stop_gradient)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)
