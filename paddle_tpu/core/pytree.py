"""Tensor-aware pytree flatten/unflatten helpers.

One shared implementation of the "strip Tensors to jax.Arrays at a trace
boundary, re-box on the way out" pattern.  Currently used by the
structured control-flow ops; the older inline copies in jit/api.py and
jit/train_step.py should migrate here as they are touched."""

from __future__ import annotations

import jax

from .tensor import Tensor


def is_tensor_leaf(x):
    return isinstance(x, Tensor)


def flatten_tensors(tree):
    """-> (raw_leaves, treedef, is_tensor_flags)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree,
                                                 is_leaf=is_tensor_leaf)
    flags = [is_tensor_leaf(l) for l in leaves]
    raw = [l._value if f else l for l, f in zip(leaves, flags)]
    return raw, treedef, flags


def unflatten_tensors(raw_leaves, treedef, flags):
    rebuilt = [Tensor(v) if f else v for v, f in zip(raw_leaves, flags)]
    return jax.tree_util.tree_unflatten(treedef, rebuilt)
