"""Dtype system.

Maps the reference's ``phi::DataType`` (``paddle/phi/common/data_type.h``) onto
JAX/numpy dtypes.  Dtypes are exposed both as objects (``paddle_tpu.float32``)
and accepted as strings (``'float32'``), matching the reference Python API.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (numpy dtype instances — what jnp arrays report).
# TPU-native dtype policy: the widest integer/float on the compute path is
# 32-bit (TPUs have no 64-bit ALU path worth using; XLA x64 stays disabled).
# 'int64'/'float64' are accepted everywhere as ALIASES of the 32-bit types —
# the same "accept the name, run 32-bit" policy the reference applies on
# accelerators that lack fp64.
bool_ = jnp.dtype(jnp.bool_)
uint8 = jnp.dtype(jnp.uint8)
int8 = jnp.dtype(jnp.int8)
int16 = jnp.dtype(jnp.int16)
int32 = jnp.dtype(jnp.int32)
int64 = jnp.dtype(jnp.int32)
float16 = jnp.dtype(jnp.float16)
bfloat16 = jnp.dtype(jnp.bfloat16)
float32 = jnp.dtype(jnp.float32)
float64 = jnp.dtype(jnp.float32)
complex64 = jnp.dtype(jnp.complex64)
complex128 = jnp.dtype(jnp.complex64)
float8_e4m3fn = jnp.dtype(jnp.float8_e4m3fn)
float8_e5m2 = jnp.dtype(jnp.float8_e5m2)

_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float": float32,
    "float64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

FLOATING = (float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2)
INTEGER = (uint8, int8, int16, int32, int64)
COMPLEX = (complex64, complex128)


def convert_dtype(dtype) -> jnp.dtype:
    """Normalise a dtype spec (str | np/jnp dtype | python type) to a dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        try:
            return _ALIASES[dtype]
        except KeyError:
            raise ValueError(f"unsupported dtype string {dtype!r}") from None
    if dtype is bool:
        return bool_
    if dtype is int:
        return int64
    if dtype is float:
        return float32
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype."""
    d = convert_dtype(dtype)
    return np.dtype(d).name if d != bfloat16 else "bfloat16"


def is_floating_point(dtype) -> bool:
    return convert_dtype(dtype) in FLOATING


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in INTEGER


def is_complex(dtype) -> bool:
    return convert_dtype(dtype) in COMPLEX


_default_dtype = float32


def set_default_dtype(d):
    """Mirror ``paddle.set_default_dtype``."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise ValueError(f"default dtype must be floating, got {dtype_name(d)}")
    _default_dtype = d


def get_default_dtype():
    """Mirror ``paddle.get_default_dtype`` (returns canonical string)."""
    return dtype_name(_default_dtype)


def default_float_dtype():
    return _default_dtype
