"""Random number generator state.

TPU-native analogue of ``phi::Generator`` (``paddle/phi/core/generator.h``):
the reference keeps a per-device (seed, philox-offset) pair; ops draw by
advancing the offset.  JAX PRNG is already counter-based (threefry), so the
natural mapping is: ``state = (base_key, offset)``; each draw folds the
current offset into the base key and bumps the offset.  This gives the same
"global seed + stateful stream" UX as the reference while every individual
key is pure, so drawn ops remain jit-traceable.
"""

from __future__ import annotations

import threading
from typing import Optional

import jax


class Generator:
    """Stateful RNG stream over a counter-based pure PRNG."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int) -> "Generator":
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._base_key = jax.random.key(int(seed))
            self._offset = 0
        return self

    def seed(self) -> int:
        return self._seed

    def get_state(self):
        return (self._seed, self._offset)

    def set_state(self, state):
        seed, offset = state
        self.manual_seed(seed)
        self._offset = int(offset)

    def next_key(self):
        """Draw the next PRNG key (advances the offset).  Inside a to_static
        trace (trace key pushed), keys derive from the traced input key."""
        if _trace_key_stack:
            entry = _trace_key_stack[-1]
            key = jax.random.fold_in(entry[0], entry[1])
            entry[1] += 1
            return key
        with self._lock:
            offset = self._offset
            self._offset += 1
        return jax.random.fold_in(self._base_key, offset)

    def split(self, n: int):
        return jax.random.split(self.next_key(), n)


# When a trace key is pushed (by paddle_tpu.jit during to_static tracing),
# draws derive from it instead of the concrete base key, so compiled programs
# take the RNG key as an *input* and dropout masks vary per call — the
# jit-correct analogue of the reference's seeded dropout ops in static graphs.
_trace_key_stack = []


def push_trace_key(key):
    _trace_key_stack.append([key, 0])


def pop_trace_key():
    _trace_key_stack.pop()


_default_generator: Optional[Generator] = None
_lock = threading.Lock()


def default_generator() -> Generator:
    global _default_generator
    if _default_generator is None:
        with _lock:
            if _default_generator is None:
                _default_generator = Generator(0)
    return _default_generator


def seed(value: int) -> Generator:
    """Mirror ``paddle.seed``: reset the global generator."""
    gen = default_generator()
    gen.manual_seed(value)
    return gen


def get_rng_state():
    return [default_generator().get_state()]


def set_rng_state(states):
    default_generator().set_state(states[0])


def next_key():
    if _trace_key_stack:
        entry = _trace_key_stack[-1]
        key = jax.random.fold_in(entry[0], entry[1])
        entry[1] += 1
        return key
    return default_generator().next_key()
