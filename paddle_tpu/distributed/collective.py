"""Collective communication API.

Analogue of ``python/paddle/distributed/communication/`` (all_reduce,
all_gather, reduce_scatter, alltoall, broadcast, send/recv — reference
ProcessGroup surface, process_group.h:53).

TPU-native semantics: collectives are *compiler* operations.  Inside a
``shard_map``/``pjit`` region (where named mesh axes are bound) these lower
to XLA collectives riding ICI (psum/all_gather/ppermute/reduce_scatter).
Outside such a region on a single process they are identities over the one
logical array — matching the reference's behavior when world_size == 1.
Outside compiled regions with a >1-process world, an eager STORE-BACKED
data plane (``eager_comm.py``, Gloo-analogue over the native TCPStore)
carries the reference's eager semantics — multi-process debugging,
LocalSGD parameter averaging, small host-side synchronization.  Install
it with ``paddle_tpu.distributed.init_eager_comm()``; without it,
cross-process eager collectives raise with that pointer.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import dispatch
from ..core.tensor import Tensor

__all__ = [
    "ReduceOp", "all_reduce", "all_gather", "all_gather_object", "reduce",
    "reduce_scatter", "alltoall", "alltoall_single", "broadcast", "scatter",
    "send", "recv", "isend", "irecv", "barrier", "wait", "stream",
    "new_group", "get_group", "destroy_process_group", "P2POp",
    "batch_isend_irecv",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _axis_of(group):
    if group is None:
        from .topology import get_hybrid_communicate_group
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            return None  # default world group: all axes — handled per-op
        return None
    return getattr(group, "axis_name", None)


def _in_shard_map(axis) -> bool:
    try:
        lax.axis_size(axis)
        return True
    except Exception:
        return False


def _world_size(group):
    if group is None:
        from .env import get_world_size
        return get_world_size()
    return group.nranks


class _Task:
    """Completed-task handle (XLA collectives are synchronous in-program)."""

    def wait(self):
        return True

    def is_completed(self):
        return True


def _not_in_group(group) -> bool:
    """Reference semantics: collectives on a group this rank is not a
    member of are no-ops."""
    ranks = getattr(group, "ranks", None)
    if not ranks:
        return False
    from .env import get_rank
    return get_rank() not in ranks


def _eager_plane(group):
    """Store-backed eager data plane when installed and world > 1.
    Subgroups get a SCOPED plane (group-local rank/world + key prefix)
    over the same store, so a 2-rank group inside a 4-rank world never
    blocks on non-members."""
    if _world_size(group) <= 1:
        return None
    from .eager_comm import EagerComm, get_eager_comm
    base = get_eager_comm()
    if base is None:
        return None
    ranks = getattr(group, "ranks", None)
    if group is None or not ranks:
        return base
    cached = getattr(group, "_eager_plane", None)
    if cached is None:
        from .env import get_rank
        gid = getattr(group, "id", id(group))
        cached = EagerComm(base.store, ranks.index(get_rank()),
                           len(ranks), prefix=f"ec/g{gid}")
        group._eager_plane = cached
    return cached


_NO_PLANE_MSG = (
    "{name} across a >1-rank group outside shard_map/pjit needs the eager "
    "data plane: call paddle_tpu.distributed.init_eager_comm() after "
    "init_parallel_env() (store-backed, for host-side/debug use), or run "
    "the step compiled where XLA collectives apply.")


def _collective(name, x, group, inside_fn, identity_ok=True):
    axis = _axis_of(group)
    if axis is not None and _in_shard_map(axis):
        return dispatch(name, lambda a: inside_fn(a, axis), (x,))
    if _world_size(group) == 1 or identity_ok:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    raise RuntimeError(
        f"{name} across a >1-rank group must run inside shard_map/pjit with "
        f"the mesh axis {axis!r} bound; eager cross-device collectives do "
        "not exist on TPU — wrap the step with paddle_tpu.distributed."
        "shard_map_over or compile it with paddle_tpu.jit")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    axis = _axis_of(group)
    if axis is not None and _in_shard_map(axis):
        def inside(a, ax):
            if op == ReduceOp.SUM:
                return lax.psum(a, ax)
            if op == ReduceOp.MAX:
                return lax.pmax(a, ax)
            if op == ReduceOp.MIN:
                return lax.pmin(a, ax)
            if op == ReduceOp.AVG:
                return lax.pmean(a, ax)
            if op == ReduceOp.PROD:
                return jnp.exp(lax.psum(jnp.log(a), ax))
            raise ValueError(op)

        out = dispatch("all_reduce", lambda a: inside(a, axis), (tensor,))
        if isinstance(tensor, Tensor):
            tensor._in_place_update(out)
        return _Task()
    if _world_size(group) == 1:
        return _Task()
    plane = _eager_plane(group)
    if plane is not None:
        import numpy as np
        arr = np.asarray(tensor._value if isinstance(tensor, Tensor)
                         else tensor)
        reduced = plane.all_reduce(arr, op)
        if isinstance(tensor, Tensor):
            tensor._in_place_update(Tensor(jnp.asarray(reduced)))
        return _Task()
    raise RuntimeError(_NO_PLANE_MSG.format(name="all_reduce"))


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # SPMD: reduce == all_reduce (every shard holds the result)
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    if _not_in_group(group):
        return _Task()
    ax_name = _axis_of(group)
    if ax_name is not None and _in_shard_map(ax_name):
        out = dispatch(
            "all_gather",
            lambda a: lax.all_gather(a, ax_name, axis=0), (tensor,))
        n = _world_size(group)
        if isinstance(tensor_list, list):
            for i in range(n):
                tensor_list.append(out[i])
        return _Task()
    if _world_size(group) == 1:
        if isinstance(tensor_list, list):
            tensor_list.append(tensor)
        return _Task()
    plane = _eager_plane(group)
    if plane is not None:
        import numpy as np
        arr = np.asarray(tensor._value if isinstance(tensor, Tensor)
                         else tensor)
        for peer in plane.all_gather(arr):
            tensor_list.append(Tensor(jnp.asarray(peer)))
        return _Task()
    raise RuntimeError(_NO_PLANE_MSG.format(name="all_gather"))


def all_gather_object(object_list, obj, group=None):
    if _not_in_group(group):
        return
    if _world_size(group) == 1:
        object_list.append(obj)
        return
    plane = _eager_plane(group)
    if plane is not None:
        object_list.extend(plane.all_gather_object(obj))
        return
    raise RuntimeError(_NO_PLANE_MSG.format(name="all_gather_object"))


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    if _not_in_group(group):
        return _Task()
    ax_name = _axis_of(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat
        src = concat(list(src), axis=0)
    if ax_name is not None and _in_shard_map(ax_name):
        out = dispatch(
            "reduce_scatter",
            lambda a: lax.psum_scatter(a, ax_name, scatter_dimension=0,
                                       tiled=True),
            (src,))
        tensor._in_place_update(out)
        return _Task()
    if _world_size(group) == 1:
        tensor._in_place_update(src if isinstance(src, Tensor)
                                else Tensor(jnp.asarray(src)))
        return _Task()
    raise RuntimeError("reduce_scatter outside shard_map on a >1 group")


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    ax_name = _axis_of(group)
    from ..tensor.manipulation import concat, split
    n = _world_size(group)
    if ax_name is not None and _in_shard_map(ax_name):
        stacked = concat([t.unsqueeze(0) for t in in_tensor_list], axis=0)
        out = dispatch(
            "alltoall",
            lambda a: lax.all_to_all(a, ax_name, split_axis=0, concat_axis=0,
                                     tiled=False),
            (stacked,))
        for i in range(n):
            out_tensor_list.append(out[i])
        return _Task()
    if n == 1:
        out_tensor_list.extend(in_tensor_list)
        return _Task()
    raise RuntimeError("alltoall outside shard_map on a >1 group")


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    ax_name = _axis_of(group)
    if ax_name is not None and _in_shard_map(ax_name):
        out = dispatch(
            "alltoall_single",
            lambda a: lax.all_to_all(
                a.reshape((_world_size(group), -1) + a.shape[1:]),
                ax_name, split_axis=0, concat_axis=0, tiled=False
            ).reshape(a.shape),
            (in_tensor,))
        out_tensor._in_place_update(out)
        return _Task()
    if _world_size(group) == 1:
        out_tensor._in_place_update(in_tensor)
        return _Task()
    raise RuntimeError("alltoall_single outside shard_map on a >1 group")


def broadcast(tensor, src, group=None, sync_op=True):
    # SPMD in-program: all shards already hold replicated values — identity.
    if _not_in_group(group):
        return _Task()
    plane = _eager_plane(group)
    if plane is not None:
        import numpy as np
        if isinstance(tensor, Tensor):
            if isinstance(tensor._value, jax.core.Tracer):
                return _Task()
            out = plane.broadcast(np.asarray(tensor._value), src)
            tensor._in_place_update(Tensor(jnp.asarray(out)))
        else:  # raw numpy arrays are mutated in place
            arr = np.asarray(tensor)
            np.copyto(arr, plane.broadcast(arr, src))
    return _Task()


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    if _world_size(group) == 1:
        if tensor_list:
            tensor._in_place_update(tensor_list[0])
        return _Task()
    ax_name = _axis_of(group)
    if ax_name is not None and _in_shard_map(ax_name):
        from ..tensor.manipulation import concat
        stacked = concat([t.unsqueeze(0) for t in tensor_list], axis=0)
        idx = lax.axis_index(ax_name)
        out = dispatch("scatter_coll", lambda a: a[idx], (stacked,))
        tensor._in_place_update(out)
        return _Task()
    raise RuntimeError("scatter outside shard_map on a >1 group")


def send(tensor, dst=0, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    ax_name = _axis_of(group)
    if ax_name is not None and _in_shard_map(ax_name):
        raise RuntimeError(
            "point-to-point send/recv inside shard_map should use "
            "paddle_tpu.distributed.p2p.ppermute_send_recv (collective_permute)")
    if _world_size(group) == 1:
        return _Task()
    plane = _eager_plane(group)
    if plane is not None:
        import numpy as np
        plane.send(np.asarray(tensor._value if isinstance(tensor, Tensor)
                              else tensor), dst)
        return _Task()
    raise RuntimeError(_NO_PLANE_MSG.format(name="send"))


def recv(tensor, src=0, group=None, sync_op=True):
    if _not_in_group(group):
        return _Task()
    if _world_size(group) == 1:
        return _Task()
    plane = _eager_plane(group)
    if plane is not None:
        import numpy as np
        out = plane.recv(src)
        if isinstance(tensor, Tensor):
            tensor._in_place_update(Tensor(jnp.asarray(out)))
        else:
            np.copyto(np.asarray(tensor), out)
        return _Task()
    raise RuntimeError(_NO_PLANE_MSG.format(name="recv"))


isend = send
irecv = recv


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    return [_Task() for _ in p2p_op_list]


def barrier(group=None):
    jax.effects_barrier()
    return _Task()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._value.block_until_ready()


class stream:
    """paddle.distributed.stream namespace parity: collectives with explicit
    stream control collapse to the standard ops (XLA owns scheduling)."""

    all_reduce = staticmethod(all_reduce)
    all_gather = staticmethod(all_gather)
    reduce_scatter = staticmethod(reduce_scatter)
    alltoall = staticmethod(alltoall)
    broadcast = staticmethod(broadcast)
    send = staticmethod(send)
    recv = staticmethod(recv)


_groups = {}
_next_gid = [1]


def new_group(ranks=None, backend=None, timeout=None):
    """Create a logical group over explicit ranks.  On the TPU mesh, prefer
    axis groups from HybridCommunicateGroup; explicit-rank groups map to a
    sub-axis only when contiguous and uniform."""
    from .topology import _AxisGroup
    ranks = list(ranks) if ranks is not None else None
    gid = _next_gid[0]
    _next_gid[0] += 1

    class _ExplicitGroup:
        def __init__(self):
            self.id = gid
            self.ranks = ranks or []
            self.nranks = len(self.ranks) if self.ranks else 1
            from .env import get_rank
            self.rank = (self.ranks.index(get_rank())
                         if self.ranks and get_rank() in self.ranks else 0)
            self.axis_name = None

        def get_group_rank(self, r):
            return self.ranks.index(r) if r in self.ranks else -1

    g = _ExplicitGroup()
    _groups[gid] = g
    return g


def get_group(gid):
    return _groups.get(gid)


def destroy_process_group(group=None):
    if group is None:
        _groups.clear()
    else:
        _groups.pop(getattr(group, "id", None), None)
