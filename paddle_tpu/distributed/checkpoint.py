"""Distributed checkpointing: per-rank shard save, merge, and reshard
across parallel layouts.

Capability analogue of the reference's auto-parallel distributed saver +
converter (``python/paddle/distributed/auto_parallel/static/
{dist_saver.py,converter.py}``: per-rank shard files with dist-attr
metadata, merged/resharded on load when the target parallel layout
differs) and the per-rank shard saves in group_sharded.

Layout: ``<dir>/meta.json`` records every tensor's global shape and shard
axis; ``<dir>/rank_<i>.npz`` holds rank-local shards.  Merge/reshard are
host-side numpy ops (the reference converter is similarly host-side);
loading onto a live mesh goes through the normal set_state_dict after
resharding to the target layout.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

import numpy as np

from ..core.tensor import Tensor

__all__ = ["ShardSpec", "save_sharded_state_dict", "load_merged_state_dict",
           "reshard_checkpoint", "load_sharded_state_dict"]


class ShardSpec:
    """How one tensor is split: ``axis`` over ``world`` ranks (axis=None
    means replicated — only rank 0's copy is kept on merge)."""

    def __init__(self, axis: Optional[int], world: int):
        self.axis = axis
        self.world = world

    def to_json(self):
        return {"axis": self.axis, "world": self.world}

    @staticmethod
    def from_json(d):
        return ShardSpec(d["axis"], d["world"])


def _as_np(t):
    return np.asarray(t._value if isinstance(t, Tensor) else t)


def save_sharded_state_dict(state_dict: Dict, path: str, rank: int,
                            shard_specs: Dict[str, ShardSpec] = None):
    """Save this rank's view.  ``shard_specs[name]`` marks tensors that are
    rank-local shards; unlisted tensors are treated as replicated."""
    os.makedirs(path, exist_ok=True)
    shard_specs = shard_specs or {}
    arrays, meta = {}, {}
    for name, value in state_dict.items():
        arr = _as_np(value)
        spec = shard_specs.get(name)
        if spec is not None and spec.axis is not None:
            global_shape = list(arr.shape)
            global_shape[spec.axis] *= spec.world
            meta[name] = {"spec": spec.to_json(),
                          "global_shape": global_shape,
                          "dtype": str(arr.dtype)}
            arrays[name] = arr
        else:
            meta[name] = {"spec": ShardSpec(None, 1).to_json(),
                          "global_shape": list(arr.shape),
                          "dtype": str(arr.dtype)}
            if rank == 0:
                arrays[name] = arr
    np.savez(os.path.join(path, f"rank_{rank}.npz"), **arrays)
    # every rank computes identical metadata; write-to-temp + atomic rename
    # makes concurrent saves race-free (last writer wins with valid JSON)
    meta_path = os.path.join(path, "meta.json")
    tmp_path = os.path.join(path, f".meta.json.tmp.{rank}")
    with open(tmp_path, "w") as f:
        json.dump(meta, f)
    os.replace(tmp_path, meta_path)


def _read_meta(path: str) -> Dict:
    with open(os.path.join(path, "meta.json")) as f:
        return json.load(f)


def load_merged_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Merge all rank shards back into full (replicated-layout) arrays —
    the converter.py merge direction."""
    meta = _read_meta(path)
    ranks = sorted(
        int(f[len("rank_"):-len(".npz")])
        for f in os.listdir(path)
        if f.startswith("rank_") and f.endswith(".npz"))
    if not ranks:
        raise FileNotFoundError(f"no rank_*.npz shards under {path}")
    per_rank = {r: np.load(os.path.join(path, f"rank_{r}.npz"))
                for r in ranks}
    try:
        merged = {}
        for name, info in meta.items():
            spec = ShardSpec.from_json(info["spec"])
            if spec.axis is None:
                if 0 not in per_rank or name not in per_rank[0]:
                    raise ValueError(
                        f"checkpoint {path!r} is missing rank_0.npz (or "
                        f"{name!r} within it) — replicated tensors are "
                        "stored on rank 0 only")
                merged[name] = per_rank[0][name]
            else:
                missing = [r for r in range(spec.world)
                           if r not in per_rank or name not in per_rank[r]]
                if missing:
                    raise ValueError(
                        f"checkpoint {path!r} is missing shards of "
                        f"{name!r} for ranks {missing}")
                merged[name] = np.concatenate(
                    [per_rank[r][name] for r in range(spec.world)],
                    axis=spec.axis)
                if list(merged[name].shape) != info["global_shape"]:
                    raise ValueError(
                        f"merged shape {list(merged[name].shape)} of "
                        f"{name!r} != recorded global shape "
                        f"{info['global_shape']}")
        return merged
    finally:
        for f in per_rank.values():
            f.close()


def load_sharded_state_dict(path: str, rank: int, target_specs:
                            Dict[str, ShardSpec]) -> Dict[str, np.ndarray]:
    """Load resharded for this rank under a (possibly different) target
    layout — the converter.py reshard-on-load direction."""
    merged = load_merged_state_dict(path)
    out = {}
    for name, arr in merged.items():
        spec = target_specs.get(name)
        if spec is None or spec.axis is None:
            out[name] = arr
        else:
            if arr.shape[spec.axis] % spec.world:
                raise ValueError(
                    f"{name!r} axis {spec.axis} (= {arr.shape[spec.axis]}) "
                    f"not divisible by target world {spec.world}")
            out[name] = np.split(arr, spec.world, axis=spec.axis)[rank]
    return out


def reshard_checkpoint(src_path: str, dst_path: str,
                       target_specs: Dict[str, ShardSpec],
                       target_world: int):
    """Offline layout conversion: read a checkpoint saved under one
    parallel layout and write it under another (pp/mp/sharding degree
    changes between runs — the reference converter's headline use)."""
    for name, spec in target_specs.items():
        if spec.axis is not None and spec.world != target_world:
            raise ValueError(
                f"target spec for {name!r} has world={spec.world} but "
                f"target_world={target_world}; all {target_world} shards "
                "must be written or the checkpoint would be incomplete")
    # merge once, split per rank (not a per-rank re-read of the source)
    merged = load_merged_state_dict(src_path)
    for rank in range(target_world):
        shard = {}
        for name, arr in merged.items():
            spec = target_specs.get(name)
            if spec is None or spec.axis is None:
                shard[name] = arr
            else:
                if arr.shape[spec.axis] % spec.world:
                    raise ValueError(
                        f"{name!r} axis {spec.axis} "
                        f"(= {arr.shape[spec.axis]}) not divisible by "
                        f"target world {spec.world}")
                shard[name] = np.split(arr, spec.world,
                                       axis=spec.axis)[rank]
        save_sharded_state_dict(shard, dst_path, rank, target_specs)
