"""Async PS communicator (reference:
``paddle/fluid/distributed/ps/service/communicator/communicator.h`` —
AsyncCommunicator: send queues per variable, a background thread batching
and merging gradients before pushing to the servers).

Trainer threads call :meth:`push_dense`/:meth:`push_sparse`, which
enqueue and return immediately; the communicator thread drains the queue,
MERGES pending gradients (dense: summed; sparse: concatenated and
pre-summed by key) and issues the actual client pushes.  ``flush`` (and
``stop``) drain everything synchronously — the reference's barrier
semantics before pull/evaluation.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["AsyncCommunicator"]


class _DensePush:
    __slots__ = ("table", "grad", "lr")

    def __init__(self, table, grad, lr):
        self.table, self.grad, self.lr = table, grad, lr


class _SparsePush:
    __slots__ = ("table", "keys", "grads", "lr")

    def __init__(self, table, keys, grads, lr):
        self.table, self.keys, self.grads, self.lr = table, keys, grads, lr


class AsyncCommunicator:
    def __init__(self, client, queue_size: int = 1024,
                 merge_size: int = 8):
        """``merge_size``: max pending pushes merged into one wire
        request (reference send_merge_var_nums)."""
        self._client = client
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._merge = max(1, merge_size)
        self._err = None
        self._running = True
        # in-flight counter (queued + being-processed): a queue-emptiness
        # signal would race with push (enqueue after the worker's empty
        # check would slip past flush)
        self._pending = 0
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _enqueue(self, item):
        self._raise_if_failed()
        with self._cv:
            self._pending += 1
        self._q.put(item)

    # -- trainer-facing (non-blocking) --------------------------------
    def push_dense(self, table_id, grad, lr):
        self._enqueue(_DensePush(int(table_id),
                                 np.asarray(grad, np.float32).reshape(-1),
                                 float(lr)))

    def push_sparse(self, table_id, keys, grads, lr):
        self._enqueue(_SparsePush(int(table_id),
                                  np.ascontiguousarray(keys, np.uint64),
                                  np.ascontiguousarray(grads, np.float32),
                                  float(lr)))

    def flush(self, timeout: float = 60.0):
        """Block until every queued push reached the servers."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._pending == 0, timeout):
                raise TimeoutError("AsyncCommunicator.flush timed out")
        self._raise_if_failed()

    def stop(self):
        if self._running:
            self.flush()
            self._running = False
            self._q.put(None)
            self._thread.join(timeout=10.0)

    # -- background thread --------------------------------------------
    def _raise_if_failed(self):
        if self._err is not None:
            raise RuntimeError(
                f"AsyncCommunicator background push failed: {self._err}")

    def _drain_batch(self, first):
        batch = [first]
        while len(batch) < self._merge:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                self._q.put(None)  # keep the stop sentinel
                break
            batch.append(item)
        return batch

    def _send(self, batch):
        # merge dense by (table, lr): grads sum (linear updates commute)
        dense = {}
        sparse = {}
        for it in batch:
            if isinstance(it, _DensePush):
                key = (it.table, it.lr)
                dense[key] = it.grad if key not in dense \
                    else dense[key] + it.grad
            else:
                key = (it.table, it.lr)
                sparse.setdefault(key, []).append(it)
        for (table, lr), grad in dense.items():
            self._client.push_dense_grad(table, grad, lr)
        for (table, lr), items in sparse.items():
            keys = np.concatenate([it.keys for it in items])
            grads = np.concatenate([it.grads for it in items], axis=0)
            # pre-sum duplicate keys: one row per key on the wire
            order = np.argsort(keys, kind="stable")
            keys_sorted = keys[order]
            uniq, start = np.unique(keys_sorted, return_index=True)
            summed = np.add.reduceat(grads[order], start, axis=0)
            self._client.push_sparse_grad(table, uniq, summed, lr)

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                break
            batch = self._drain_batch(item)
            try:
                self._send(batch)
            except Exception as e:  # surface on the trainer thread
                self._err = e
            finally:
                with self._cv:
                    self._pending -= len(batch)
                    if self._pending <= 0:
                        self._cv.notify_all()


class GeoCommunicator:
    """Geo-SGD trainer mode (reference ``ps/communicator/communicator.h``
    GeoCommunicator + ``fleet/meta_optimizers`` a_sync with k_steps>0):
    workers train LOCALLY for ``push_every`` steps, then exchange only the
    parameter DELTA since the last sync — the server accumulates deltas
    from every worker (its table is the shared model), and the worker
    rebases onto the fresh server state.  Staleness-tolerant, one
    round-trip per k steps instead of per step.
    """

    def __init__(self, client, parameters, base_table_id: int = 1000,
                 push_every: int = 10):
        import jax.numpy as jnp
        import numpy as np
        self._client = client
        self._params = list(parameters)
        self._push_every = max(1, int(push_every))
        self._tables = {}
        self._snapshots = {}
        self._count = 0
        # explicit initialized-marker table: an all-zero trained table must
        # not be mistaken for a fresh one (create is idempotent server-side)
        self._marker_tid = base_table_id + 999983
        client.create_dense_table(self._marker_tid, 1)
        fresh = not np.any(client.pull_dense(self._marker_tid))
        for i, p in enumerate(self._params):
            tid = base_table_id + i
            vals = np.asarray(p._value, np.float32).reshape(-1)
            client.create_dense_table(tid, vals.size)
            if fresh:
                client.set_dense(tid, vals)  # first worker seeds the init
            else:
                # late-joining worker ADOPTS accumulated server state
                p._value = jnp.asarray(
                    client.pull_dense(tid).reshape(p._value.shape),
                    p._value.dtype)
            self._tables[id(p)] = tid
            # snapshot what the param ACTUALLY stores post-cast, so low
            # precision params don't push rounding noise as deltas
            self._snapshots[id(p)] = np.asarray(
                p._value, np.float32).reshape(-1).copy()
        if fresh:
            client.set_dense(self._marker_tid,
                             np.ones(1, np.float32))

    def step(self):
        """Call once per optimizer step; syncs every push_every calls."""
        self._count += 1
        if self._count % self._push_every == 0:
            self.sync()

    def sync(self):
        import jax.numpy as jnp
        import numpy as np
        for p in self._params:
            tid = self._tables[id(p)]
            local = np.asarray(p._value, np.float32).reshape(-1)
            delta = local - self._snapshots[id(p)]
            # server computes w -= lr * grad; lr=1, grad=-delta -> w += delta
            self._client.push_dense_grad(tid, -delta, lr=1.0)
            fresh = self._client.pull_dense(tid)
            p._value = jnp.asarray(
                fresh.reshape(p._value.shape), p._value.dtype)
            # snapshot the post-cast value (see __init__)
            self._snapshots[id(p)] = np.asarray(
                p._value, np.float32).reshape(-1).copy()
