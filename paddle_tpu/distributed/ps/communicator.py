"""Async PS communicator (reference:
``paddle/fluid/distributed/ps/service/communicator/communicator.h`` —
AsyncCommunicator: send queues per variable, a background thread batching
and merging gradients before pushing to the servers).

Trainer threads call :meth:`push_dense`/:meth:`push_sparse`, which
enqueue and return immediately; the communicator thread drains the queue,
MERGES pending gradients (dense: summed; sparse: concatenated and
pre-summed by key) and issues the actual client pushes.  ``flush`` (and
``stop``) drain everything synchronously — the reference's barrier
semantics before pull/evaluation.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["AsyncCommunicator"]


class _DensePush:
    __slots__ = ("table", "grad", "lr")

    def __init__(self, table, grad, lr):
        self.table, self.grad, self.lr = table, grad, lr


class _SparsePush:
    __slots__ = ("table", "keys", "grads", "lr")

    def __init__(self, table, keys, grads, lr):
        self.table, self.keys, self.grads, self.lr = table, keys, grads, lr


class AsyncCommunicator:
    def __init__(self, client, queue_size: int = 1024,
                 merge_size: int = 8):
        """``merge_size``: max pending pushes merged into one wire
        request (reference send_merge_var_nums)."""
        self._client = client
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._merge = max(1, merge_size)
        self._err = None
        self._running = True
        # in-flight counter (queued + being-processed): a queue-emptiness
        # signal would race with push (enqueue after the worker's empty
        # check would slip past flush)
        self._pending = 0
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _enqueue(self, item):
        self._raise_if_failed()
        with self._cv:
            self._pending += 1
        self._q.put(item)

    # -- trainer-facing (non-blocking) --------------------------------
    def push_dense(self, table_id, grad, lr):
        self._enqueue(_DensePush(int(table_id),
                                 np.asarray(grad, np.float32).reshape(-1),
                                 float(lr)))

    def push_sparse(self, table_id, keys, grads, lr):
        self._enqueue(_SparsePush(int(table_id),
                                  np.ascontiguousarray(keys, np.uint64),
                                  np.ascontiguousarray(grads, np.float32),
                                  float(lr)))

    def flush(self, timeout: float = 60.0):
        """Block until every queued push reached the servers."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._pending == 0, timeout):
                raise TimeoutError("AsyncCommunicator.flush timed out")
        self._raise_if_failed()

    def stop(self):
        if self._running:
            self.flush()
            self._running = False
            self._q.put(None)
            self._thread.join(timeout=10.0)

    # -- background thread --------------------------------------------
    def _raise_if_failed(self):
        if self._err is not None:
            raise RuntimeError(
                f"AsyncCommunicator background push failed: {self._err}")

    def _drain_batch(self, first):
        batch = [first]
        while len(batch) < self._merge:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                self._q.put(None)  # keep the stop sentinel
                break
            batch.append(item)
        return batch

    def _send(self, batch):
        # merge dense by (table, lr): grads sum (linear updates commute)
        dense = {}
        sparse = {}
        for it in batch:
            if isinstance(it, _DensePush):
                key = (it.table, it.lr)
                dense[key] = it.grad if key not in dense \
                    else dense[key] + it.grad
            else:
                key = (it.table, it.lr)
                sparse.setdefault(key, []).append(it)
        for (table, lr), grad in dense.items():
            self._client.push_dense_grad(table, grad, lr)
        for (table, lr), items in sparse.items():
            keys = np.concatenate([it.keys for it in items])
            grads = np.concatenate([it.grads for it in items], axis=0)
            # pre-sum duplicate keys: one row per key on the wire
            order = np.argsort(keys, kind="stable")
            keys_sorted = keys[order]
            uniq, start = np.unique(keys_sorted, return_index=True)
            summed = np.add.reduceat(grads[order], start, axis=0)
            self._client.push_sparse_grad(table, uniq, summed, lr)

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                break
            batch = self._drain_batch(item)
            try:
                self._send(batch)
            except Exception as e:  # surface on the trainer thread
                self._err = e
            finally:
                with self._cv:
                    self._pending -= len(batch)
                    if self._pending <= 0:
                        self._cv.notify_all()
