"""Parameter-server training (capability analogue of
``python/paddle/distributed/ps`` + the C++ PS in
``paddle/fluid/distributed/ps/``).

Architecture: the native TCP parameter server
(runtime/native/ps_server.cc ≙ brpc_ps_server.h) owns dense and sparse
float tables and applies the SGD rule server-side
(≙ table/sparse_sgd_rule.h); trainers hold :class:`PSClient` connections
and embed :class:`SparseEmbedding` layers whose forward pulls rows for
the batch's ids and whose backward pushes gradients — the async-push
semantics of the reference's communicator collapse to synchronous
push-on-backward here (the "sync mode" of the_one_ps), which is the
honest starting point on TPU hosts.
"""

from __future__ import annotations

import numpy as np

from ...runtime.native_bindings import PSServerHandle, PSClientHandle
from ...autograd.py_layer import PyLayer
from ...core.tensor import Tensor
from ...nn import Layer

__all__ = ["PSServer", "PSClient", "ShardedPSClient",
           "SparseEmbedding", "DensePSParameter", "AsyncCommunicator",
           "GeoCommunicator"]

from .communicator import AsyncCommunicator, GeoCommunicator  # noqa: E402


class PSServer:
    """Run the native parameter server (usually on the trainer-0 host or a
    dedicated CPU node; reference: ``fleet.init_server()``/run_server)."""

    def __init__(self, port: int = 0):
        self._handle = PSServerHandle(port)

    @property
    def port(self) -> int:
        return self._handle.port

    def stop(self):
        self._handle.stop()


class PSClient:
    """Trainer-side client (reference ``PSClient``/``brpc_ps_client.h``)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 timeout_s: float = 30.0):
        self._c = PSClientHandle(host, port, timeout_s)
        self._dense_dims = {}
        self._sparse_dims = {}

    # table management -------------------------------------------------
    def create_dense_table(self, table_id: int, dim: int, init=None):
        self._c.create_dense(table_id, dim)
        self._dense_dims[table_id] = dim
        if init is not None:
            self._c.set_dense(table_id, np.asarray(init, np.float32))

    def create_sparse_table(self, table_id: int, dim: int,
                            init_scale: float = 0.01, seed: int = 0,
                            sgd_rule: str = "sgd", eps: float = 1e-8,
                            max_mem_rows: int = 0, spill_path: str = ""):
        """``sgd_rule``: "sgd" (naive) or "adagrad" (per-feature
        accumulators, reference sparse_sgd_rule.h SparseAdaGradSGDRule).
        ``max_mem_rows``>0 caps resident rows; colder rows spill to
        ``spill_path`` with LRU eviction (reference ssd_sparse_table.h)."""
        rules = {"sgd": 0, "naive": 0, "adagrad": 1}
        if sgd_rule not in rules:
            raise ValueError(f"sgd_rule must be one of {list(rules)}, "
                             f"got {sgd_rule!r}")
        if max_mem_rows > 0 and not spill_path:
            raise ValueError(
                "create_sparse_table: max_mem_rows needs a spill_path")
        self._c.create_sparse(table_id, dim, init_scale, seed,
                              rules[sgd_rule], eps, max_mem_rows,
                              spill_path)
        self._sparse_dims[table_id] = dim

    def sparse_mem_rows(self, table_id: int) -> int:
        """Rows currently resident in server memory (spilled excluded)."""
        return self._c.sparse_mem_rows(table_id)

    # graph tables (reference common_graph_table.h:501: the PS serves the
    # graph STRUCTURE; node features ride the sparse tables) ------------
    def create_graph_table(self, table_id: int, seed: int = 0):
        self._c.create_graph(table_id, seed)

    def add_graph_edges(self, table_id: int, src, dst):
        """Append directed edges src[i] -> dst[i] (call twice with swapped
        args for an undirected graph)."""
        self._c.graph_add_edges(table_id, src, dst)

    def sample_neighbors(self, table_id: int, nodes, sample_size: int):
        """[len(nodes), sample_size] uint64 neighbor ids sampled with
        replacement server-side; isolated nodes echo themselves
        (self-loop convention — reference graph_sample_neighbors)."""
        return self._c.graph_sample_neighbors(table_id, nodes, sample_size)

    def node_degree(self, table_id: int, nodes):
        return self._c.graph_degree(table_id, nodes)

    # dense ------------------------------------------------------------
    def pull_dense(self, table_id: int):
        return self._c.pull_dense(table_id, self._dense_dims[table_id])

    def push_dense_grad(self, table_id: int, grad, lr: float):
        self._c.push_dense(table_id, grad, lr)

    def set_dense(self, table_id: int, values):
        self._c.set_dense(table_id, values)

    # sparse -----------------------------------------------------------
    def pull_sparse(self, table_id: int, keys):
        return self._c.pull_sparse(table_id, keys,
                                   self._sparse_dims[table_id])

    def push_sparse_grad(self, table_id: int, keys, grads, lr: float):
        self._c.push_sparse(table_id, keys, grads, lr)

    def sparse_table_size(self, table_id: int) -> int:
        return self._c.sparse_size(table_id)

    def close(self):
        self._c.close()


class _SparseLookup(PyLayer):
    """forward: pull rows; backward: push grads to the server (the
    reference's pull_sparse / push_sparse_grad pair around the embedding
    op, ps/service/communicator)."""

    @staticmethod
    def forward(ctx, ids, hook, client, table_id, lr):
        # `hook` is a scalar trainable dummy: PyLayer wires its node into
        # the tape only when some input requires grad, and the PS table
        # has no local Parameter (≙ the remote-table var in the reference)
        ids_np = np.asarray(ids._value if isinstance(ids, Tensor) else ids)
        flat = ids_np.reshape(-1).astype(np.uint64)
        rows = client.pull_sparse(table_id, flat)
        ctx.client = client
        ctx.table_id = table_id
        ctx.keys = flat
        ctx.lr = lr
        out = rows.reshape(ids_np.shape + (rows.shape[-1],))
        return Tensor(out, stop_gradient=False)

    @staticmethod
    def backward(ctx, grad_out):
        g = np.asarray(grad_out._value if isinstance(grad_out, Tensor)
                       else grad_out)
        g2 = g.reshape(-1, g.shape[-1])
        # duplicate ids in a batch each contribute their own gradient row;
        # the server accumulates them (one push per occurrence collapses
        # to a pre-summed push here, matching mean-free SGD accumulation)
        order = np.argsort(ctx.keys, kind="stable")
        keys_sorted = ctx.keys[order]
        uniq, start = np.unique(keys_sorted, return_index=True)
        summed = np.add.reduceat(g2[order], start, axis=0)
        ctx.client.push_sparse_grad(ctx.table_id, uniq, summed, ctx.lr)
        # grads align with tensor inputs (ids, hook): ids not
        # differentiable; hook gets zeros so optimizers see a no-op
        return None, Tensor(np.zeros(1, np.float32))


class SparseEmbedding(Layer):
    """Embedding whose table lives on the parameter server (reference:
    ``paddle.static.nn.sparse_embedding`` + memory_sparse_table).  The
    learning rate is applied server-side on push."""

    def __init__(self, client: PSClient, table_id: int, embedding_dim: int,
                 learning_rate: float = 0.01, init_scale: float = 0.01,
                 seed: int = 0):
        super().__init__()
        self.client = client
        self.table_id = table_id
        self.embedding_dim = embedding_dim
        self.learning_rate = learning_rate
        client.create_sparse_table(table_id, embedding_dim, init_scale,
                                   seed)
        self._grad_hook = self.create_parameter([1], is_bias=True)

    def forward(self, ids):
        return _SparseLookup.apply(ids, self._grad_hook, self.client,
                                   self.table_id, self.learning_rate)


class DensePSParameter:
    """A dense parameter mirrored from the server: ``sync()`` pulls the
    latest values into the local Tensor, ``push_grad()`` sends the local
    gradient (reference dense-table pull/push in the communicator)."""

    def __init__(self, client: PSClient, table_id: int, shape,
                 learning_rate: float = 0.01, init=None):
        self.client = client
        self.table_id = table_id
        self.shape = tuple(shape)
        self.learning_rate = learning_rate
        dim = int(np.prod(self.shape))
        client.create_dense_table(table_id, dim,
                                  None if init is None
                                  else np.asarray(init, np.float32)
                                  .reshape(-1))

    def sync(self) -> Tensor:
        vals = self.client.pull_dense(self.table_id)
        return Tensor(vals.reshape(self.shape))

    def push_grad(self, grad):
        g = np.asarray(grad._value if isinstance(grad, Tensor) else grad)
        self.client.push_dense_grad(self.table_id, g.reshape(-1),
                                    self.learning_rate)


class ShardedPSClient:
    """Client over multiple parameter servers (reference: the brpc client
    shards sparse keys across server instances, ps/service/ps_client.h).

    Sharding rules: sparse keys are mixed with a 64-bit multiplicative
    hash before ``% n`` (stride-patterned id spaces would otherwise
    collapse onto one server); dense tables live whole on server
    ``table_id % n``.  The surface matches :class:`PSClient` so
    SparseEmbedding/DensePSParameter work unchanged.
    """

    def __init__(self, endpoints, timeout_s: float = 30.0):
        if not endpoints:
            raise ValueError("ShardedPSClient needs at least one endpoint")
        self._clients = []
        try:
            for ep in endpoints:
                host, port = ep.rsplit(":", 1)
                self._clients.append(PSClient(host, int(port), timeout_s))
        except Exception:
            # don't leak sockets when a later endpoint is still booting
            # (workers retry init_worker in a loop during startup)
            for c in self._clients:
                c.close()
            raise
        self._n = len(self._clients)
        self._sparse_dims = {}
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(max_workers=self._n) \
            if self._n > 1 else None

    # dense: whole table on one server -----------------------------------
    def _dense_owner(self, table_id):
        return self._clients[table_id % self._n]

    def create_dense_table(self, table_id, dim, init=None):
        self._dense_owner(table_id).create_dense_table(table_id, dim, init)

    def pull_dense(self, table_id):
        return self._dense_owner(table_id).pull_dense(table_id)

    def push_dense_grad(self, table_id, grad, lr):
        self._dense_owner(table_id).push_dense_grad(table_id, grad, lr)

    def set_dense(self, table_id, values):
        self._dense_owner(table_id).set_dense(table_id, values)

    # sparse: rows hashed across all servers ------------------------------
    def create_sparse_table(self, table_id, dim, init_scale=0.01, seed=0,
                            **kwargs):
        spill = kwargs.pop("spill_path", "")
        for i, c in enumerate(self._clients):
            c.create_sparse_table(
                table_id, dim, init_scale, seed,
                spill_path=f"{spill}.shard{i}" if spill else "", **kwargs)
        self._sparse_dims[table_id] = dim

    def sparse_mem_rows(self, table_id):
        return sum(c.sparse_mem_rows(table_id) for c in self._clients)

    def _partition(self, keys):
        keys = np.ascontiguousarray(keys, np.uint64)
        # splitmix-style mixing: decorrelates strided id spaces from % n
        with np.errstate(over="ignore"):
            mixed = keys * np.uint64(0x9E3779B97F4A7C15)
        owner = ((mixed >> np.uint64(33)) % np.uint64(self._n)) \
            .astype(np.int64)
        return keys, owner

    def _fanout(self, fns):
        """Run one callable per server concurrently (latency ~max, not
        ~sum — each PSClient has its own socket+lock)."""
        if self._pool is None or len(fns) <= 1:
            return [fn() for fn in fns]
        futures = [self._pool.submit(fn) for fn in fns]
        return [f.result() for f in futures]

    def pull_sparse(self, table_id, keys):
        keys, owner = self._partition(keys)
        dim = self._sparse_dims[table_id]
        out = np.empty((keys.size, dim), np.float32)
        work = []
        for s in range(self._n):
            idx = np.nonzero(owner == s)[0]
            if idx.size:
                work.append((idx, lambda s=s, idx=idx:
                             self._clients[s].pull_sparse(table_id,
                                                          keys[idx])))
        results = self._fanout([fn for _, fn in work])
        for (idx, _), rows in zip(work, results):
            out[idx] = rows
        return out

    def push_sparse_grad(self, table_id, keys, grads, lr):
        keys, owner = self._partition(keys)
        grads = np.ascontiguousarray(grads, np.float32)
        work = []
        for s in range(self._n):
            idx = np.nonzero(owner == s)[0]
            if idx.size:
                work.append(lambda s=s, idx=idx:
                            self._clients[s].push_sparse_grad(
                                table_id, keys[idx], grads[idx], lr))
        self._fanout(work)

    def sparse_table_size(self, table_id):
        return sum(c.sparse_table_size(table_id) for c in self._clients)

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        for c in self._clients:
            c.close()
