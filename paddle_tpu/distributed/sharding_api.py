"""Dygraph semi-auto sharding API (analogue of
python/paddle/distributed/auto_parallel/api.py: shard_tensor:85, plus
shard_layer/shard_optimizer from the 2.6-era semi-auto surface).

A sharding annotation is a PartitionSpec stored on the Tensor
(``_dist_attr``).  Eagerly, ``jax.device_put`` places the value with that
NamedSharding (the analogue of DistTensor's local-shard construction);
under jit, annotations become ``lax.with_sharding_constraint`` so GSPMD
propagates layouts — the TPU-native replacement for the reference's
reshard-function library (SURVEY §2.1 DistTensor row).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.tensor import Tensor
from .topology import get_global_mesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_layer", "shard_optimizer",
           "reshard", "dtensor_from_fn", "Shard", "Replicate", "Partial"]


class Shard:
    """Placement: shard along tensor dim `dim` (reference dist.Shard)."""

    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard({self.dim})"


class Replicate:
    def __repr__(self):
        return "Replicate()"


class Partial:
    """Pending-reduction placement.  GSPMD tracks partial sums internally;
    accepted for API parity."""

    def __repr__(self):
        return "Partial()"


class ProcessMesh:
    """Analogue of paddle.distributed.ProcessMesh (dist_attr.h ProcessMesh):
    wraps a jax Mesh (or builds one from shape/axis names)."""

    def __init__(self, mesh=None, dim_names=None, shape=None, process_ids=None):
        if isinstance(mesh, Mesh):
            self.jax_mesh = mesh
            self.dim_names = list(mesh.axis_names)
        else:
            import numpy as np
            arr = np.asarray(mesh if mesh is not None else process_ids)
            shape = arr.shape if shape is None else tuple(shape)
            self.dim_names = list(dim_names or
                                  [f"d{i}" for i in range(len(shape))])
            devs = np.array(jax.devices()[:arr.size]).reshape(shape)
            self.jax_mesh = Mesh(devs, self.dim_names)

    @property
    def shape(self):
        return list(self.jax_mesh.devices.shape)

    @property
    def process_ids(self):
        return list(range(self.jax_mesh.devices.size))

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            self.jax_mesh == other.jax_mesh

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(placements, ndim, mesh):
    axes = [None] * ndim
    names = list(mesh.axis_names)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            if axes[p.dim] is None:
                axes[p.dim] = names[mesh_dim]
            elif isinstance(axes[p.dim], tuple):
                axes[p.dim] = axes[p.dim] + (names[mesh_dim],)
            else:
                axes[p.dim] = (axes[p.dim], names[mesh_dim])
    return PartitionSpec(*axes)


def _resolve_mesh(mesh):
    if mesh is None:
        m = get_global_mesh()
        if m is None:
            raise ValueError("no global mesh; build one via "
                             "HybridCommunicateGroup or pass mesh=")
        return m
    if isinstance(mesh, ProcessMesh):
        return mesh.jax_mesh
    return mesh


def shard_tensor(data, mesh=None, placements=None, dtype=None,
                 stop_gradient=None, spec: Optional[PartitionSpec] = None):
    """Annotate (and place) a tensor with a sharding.

    Accepts either reference-style ``placements`` ([Shard(0), Replicate()]
    per mesh dim) or a direct PartitionSpec via ``spec``.
    """
    jmesh = _resolve_mesh(mesh)
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(data))
    if spec is None:
        placements = placements or []
        spec = _placements_to_spec(placements, t.ndim, jmesh)
    arr = t._value
    if isinstance(arr, jax.core.Tracer):
        out_arr = jax.lax.with_sharding_constraint(
            arr, NamedSharding(jmesh, spec))
    else:
        out_arr = jax.device_put(arr, NamedSharding(jmesh, spec))
    out = Tensor(out_arr, stop_gradient=t.stop_gradient
                 if stop_gradient is None else stop_gradient)
    out._dist_attr = spec
    out._is_param = t._is_param
    return out


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh=None, placements=None, spec=None):
    """Change a tensor's sharding (reference: reshard function library,
    {r_to_s,s_to_r,...}_reshard_function.cc).  One call — XLA emits the
    minimal collective to move between layouts."""
    return shard_tensor(x, mesh, placements, spec=spec)


def shard_layer(layer, process_mesh=None, shard_fn=None, input_fn=None,
                output_fn=None):
    """Shard a layer's parameters in-place (reference
    auto_parallel/api.py shard_layer)."""
    jmesh = _resolve_mesh(process_mesh)
    if shard_fn is None:
        def shard_fn(name, l, mesh):
            return None
    for name, sub in list(layer.named_sublayers(include_self=True)):
        shard_fn(name, sub, process_mesh)
    # place any annotated params on device with their shardings
    for p in layer.parameters():
        if p._dist_attr is not None and not isinstance(p._value, jax.core.Tracer):
            p._value = jax.device_put(
                p._value, NamedSharding(jmesh, p._dist_attr))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """ZeRO-style optimizer-state sharding (reference dist.shard_optimizer):
    marks the optimizer so accumulators are created with the parameter's
    sharding (or sharded along the 'sharding' axis when the param is
    replicated). The actual placement happens under jit via GSPMD."""
    optimizer._zero_sharded = True
    return optimizer


def shard_first_divisible_dim(spec, shape, axis_size, axis_name="sharding"):
    """Shared ZeRO layout rule, used for both stage-3 param sharding and
    optimizer-state sharding so the two layouts always agree.

    Prefer STACKING ``axis_name`` onto a dim that is already sharded (the
    flatten-shard layout): for a Megatron-sharded table like a
    VocabParallelEmbedding weight (model, None), producing
    (('model','sharding'), None) keeps the hidden dim unsharded, so the
    embedding-output cotangent never needs a batch->hidden reshard (which
    the SPMD partitioner can only do by involuntary full rematerialization
    through the gather's call boundary).  Fall back to the first unsharded
    dim divisible by ``axis_size``."""
    mesh = None
    try:
        from .topology import get_global_mesh
        mesh = get_global_mesh()
    except Exception:
        pass
    for i, s in enumerate(shape):
        if spec[i] is None or spec[i] == axis_name:
            continue
        existing = spec[i] if isinstance(spec[i], tuple) else (spec[i],)
        if axis_name in existing:
            continue
        # without a mesh the existing axes' sizes are unknown — skip the
        # stacking rule rather than risk an indivisible layout
        existing_size = 0
        if mesh is not None:
            try:
                existing_size = 1
                for a in existing:
                    existing_size *= mesh.shape[a]
            except Exception:
                existing_size = 0
        if existing_size and s % (existing_size * axis_size) == 0:
            spec[i] = existing + (axis_name,)
            return spec
    for i, s in enumerate(shape):
        if spec[i] is None and s % axis_size == 0 and s >= axis_size:
            spec[i] = axis_name
            break
    return spec
