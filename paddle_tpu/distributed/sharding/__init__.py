"""``paddle_tpu.distributed.sharding`` — grouped parameter/optimizer-state
sharding, the ZeRO stages (analogue of
``python/paddle/distributed/sharding/group_sharded.py`` over
``fleet/meta_parallel/sharding/group_sharded_stage{2,3}.py:46/:59``).

TPU-native design: the reference implements ZeRO with explicit broadcast /
reduce-scatter hooks and fused param storage.  Under GSPMD the same memory
layouts are *shardings on the "sharding" mesh axis*:

- stage 1 (``"os"``): optimizer states carry a sharded layout; XLA
  reduce-scatters gradients into the sharded update and all-gathers updated
  params — exactly the stage-1 comm pattern, chosen by the compiler.
- stage 2 (``"os_g"``): same layouts; gradients never materialize replicated
  because the grad→state contraction is sharded (donated buffers).
- stage 3 (``"p_g_os"``): parameters themselves carry the sharded layout;
  XLA inserts the per-use all-gather (the reference's fwd/bwd param
  broadcast hooks, group_sharded_stage3.py:59) and frees gathered copies
  after use.
"""

from __future__ import annotations

import warnings

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..topology import get_global_mesh
from ..sharding_api import shard_optimizer, shard_first_divisible_dim

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def _shard_param_spec(shape, axis_size) -> PartitionSpec:
    """Spec sharding the first dim divisible by the sharding-axis size
    (same rule TrainStep uses for optimizer states)."""
    return PartitionSpec(
        *shard_first_divisible_dim([None] * len(shape), shape, axis_size))


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """Wrap ``model``/``optimizer`` for ZeRO sharding at ``level`` in
    {"os", "os_g", "p_g_os"}.  Returns ``(model, optimizer, scaler)``.

    ``group``/``buffer_max_size``/``segment_size``/``sync_comm`` exist for
    API parity: bucketing and comm/compute overlap are XLA's job on TPU.
    ``offload`` is accepted for parity but NOT implemented — states stay in
    HBM sharded 1/N (usually smaller than offloaded-but-replicated); a
    warning is emitted if requested.
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os|os_g|p_g_os, got {level!r}")
    shard_optimizer(optimizer)  # stages 1-2: sharded states + scattered grads
    optimizer._group_sharded_level = level
    optimizer._group_sharded_offload = bool(offload)
    if offload:
        warnings.warn("group_sharded_parallel(offload=True): host offload of "
                      "optimizer states is not implemented on this backend; "
                      "states remain in HBM sharded over the 'sharding' axis")

    if level == "p_g_os":
        mesh = get_global_mesh()
        axis = None
        if mesh is not None and "sharding" in mesh.axis_names \
                and mesh.shape["sharding"] > 1:
            axis = mesh.shape["sharding"]
        if axis is None:
            warnings.warn(
                "group_sharded_parallel(level='p_g_os'): no global mesh with "
                "a 'sharding' axis >1 is set — parameters stay replicated "
                "(stage-1/2 state sharding still applies). Build a "
                "HybridCommunicateGroup(sharding=N) first for ZeRO-3 layouts.")
        for p in model.parameters():
            if p.stop_gradient or axis is None:
                continue
            shape = p._value.shape
            spec = _shard_param_spec(shape, axis)
            if all(s is None for s in spec):
                continue
            p._dist_attr = spec
            if not isinstance(p._value, jax.core.Tracer):
                p._value = jax.device_put(p._value,
                                          NamedSharding(mesh, spec))
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """Gather sharded params/states and save full state dicts under
    ``output`` (reference ``save_group_sharded_model``: model.pdmodel /
    model.pdopt files)."""
    import os

    from ...framework.io import save

    os.makedirs(output, exist_ok=True)
    save(model.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
