"""Static cost model for semi-auto parallel planning.

Analogue of ``python/paddle/distributed/auto_parallel/static/cost/
estimate_cost.py`` (CostEstimator over the completed program) and the
plan-selection role of ``tuner/parallel_tuner.py`` — but TPU-native: costs
are estimated directly on the traced jaxpr under candidate
PartitionSpecs, with XLA/GSPMD's collective algebra (ring all-reduce
``2(n-1)/n``, all-gather/reduce-scatter ``(n-1)/n``) instead of profiled
op tables.  No trial runs: the Engine uses this to CHOOSE among
row/column/replicated splits before compiling anything (the live-trial
path remains in ``auto_tuner``).

Model (forward-pass matmul algebra; backward collectives mirror it, so
the RANKING is unchanged while absolute bytes are a lower bound):

- contract dims sharded identically on both operands -> partial sums ->
  all_reduce of the (sharded) output;
- contract dim sharded on one side, replicated on the other -> the
  replicated side is sliced locally (free) and the matmul proceeds
  sharded -> all_reduce of the output;
- conflicting axes on a contract-dim pair -> the smaller operand is
  all_gathered first;
- per-device FLOPs divide by every distinct mesh axis sharding a matmul
  dim.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

from .completion import _subjaxpr_of, propagate_jaxpr_specs

__all__ = ["PlanCost", "estimate_plan_cost", "choose_param_plan",
           "hlo_collective_bytes"]


@dataclass
class PlanCost:
    flops_per_device: float = 0.0
    comm_bytes: float = 0.0
    comm_count: int = 0
    param_bytes_per_device: float = 0.0
    breakdown: list = field(default_factory=list)

    def total(self, flops_per_s=197e12, bw_bytes_per_s=1.8e11,
              hbm_bytes_per_s=8.2e11, alpha_s=2e-6) -> float:
        """Scalar rank: compute time + ICI comm time + per-device param
        HBM read time (v5e nominal constants; only the RATIO matters for
        ranking).  The HBM term makes sharded storage strictly beat
        replicated storage when compute and comm tie (e.g. row-split vs
        replicated down-projection against a column-sharded activation).
        Collectives carry an alpha + beta*n latency model (reference
        ``auto_parallel/static/cost/comm_op_cost.py:21``): ``alpha_s``
        per collective launch on top of the byte term, so a plan
        spraying many small collectives loses to one moving the same
        bytes in fewer ops."""
        return (self.flops_per_device / flops_per_s +
                self.comm_bytes / bw_bytes_per_s +
                self.comm_count * alpha_s +
                self.param_bytes_per_device / hbm_bytes_per_s)

    def _add_comm(self, kind, opname, nbytes):
        self.comm_bytes += nbytes
        self.comm_count += 1
        self.breakdown.append((kind, opname, nbytes))


def _axes_of(entry) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(a for a in entry if a is not None)
    return (entry,)


def _axes_size(axes, mesh_shape) -> int:
    n = 1
    for a in axes:
        n *= mesh_shape.get(a, 1)
    return n


def _dtype_size(aval) -> int:
    try:
        return aval.dtype.itemsize
    except Exception:
        return 4


def _dot_cost(eqn, specs, mesh_shape, cost):
    lhs, rhs = eqn.invars[:2]
    out = eqn.outvars[0]
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    ls = specs.get(lhs) or (None,) * lhs.aval.ndim
    rs = specs.get(rhs) or (None,) * rhs.aval.ndim

    lshape, rshape, oshape = lhs.aval.shape, rhs.aval.shape, out.aval.shape
    batch = math.prod(lshape[d] for d in lb) if lb else 1
    k = math.prod(lshape[d] for d in lc) if lc else 1
    m = math.prod(d for i, d in enumerate(lshape)
                  if i not in set(lc) | set(lb))
    n = math.prod(d for i, d in enumerate(rshape)
                  if i not in set(rc) | set(rb))
    total_flops = 2 * batch * m * n * k

    sharding_axes = set()
    for i, e in enumerate(ls):
        sharding_axes.update(_axes_of(e))
    for i, e in enumerate(rs):
        sharding_axes.update(_axes_of(e))
    nshard = _axes_size(sharding_axes, mesh_shape)
    cost.flops_per_device += total_flops / max(nshard, 1)

    out_elems = math.prod(oshape) if oshape else 1
    out_bytes = out_elems * _dtype_size(out.aval)

    # an axis used for contraction cannot simultaneously shard a free dim
    # of the same matmul: the operand reusing it must be gathered first
    contract_axes = set()
    for cl, cr in zip(lc, rc):
        contract_axes.update(_axes_of(ls[cl]))
        contract_axes.update(_axes_of(rs[cr]))
    if contract_axes:
        for var, spec, cdims, bdims in ((lhs, ls, lc, lb), (rhs, rs, rc,
                                                           rb)):
            for d, e in enumerate(spec):
                if d in cdims or d in bdims:
                    continue
                reused = set(_axes_of(e)) & contract_axes
                if reused:
                    na = _axes_size(reused, mesh_shape)
                    vbytes = math.prod(var.aval.shape) * _dtype_size(
                        var.aval)
                    cost._add_comm("all_gather", eqn.primitive.name,
                                   vbytes * (na - 1) / na)

    for cl, cr in zip(lc, rc):
        al, ar = _axes_of(ls[cl]), _axes_of(rs[cr])
        if not al and not ar:
            continue
        if al and ar and al != ar:
            # conflicting contraction shardings: gather the smaller operand
            # (ring cost uses the GATHERED operand's axis size)
            lbytes = math.prod(lshape) * _dtype_size(lhs.aval)
            rbytes = math.prod(rshape) * _dtype_size(rhs.aval)
            na = _axes_size(al if lbytes < rbytes else ar, mesh_shape)
            cost._add_comm("all_gather", eqn.primitive.name,
                           min(lbytes, rbytes) * (na - 1) / na)
            continue
        axes = al or ar
        na = _axes_size(axes, mesh_shape)
        if na > 1:
            # partial sums over the contracted axis -> ring all_reduce of
            # the output (local shard of it; axes reused for contraction
            # cannot also shard the output)
            out_axes = {a for e in (specs.get(out) or ())
                        for a in _axes_of(e)} - contract_axes
            local_out = out_bytes / max(_axes_size(out_axes, mesh_shape), 1)
            cost._add_comm("all_reduce", eqn.primitive.name,
                           2 * (na - 1) / na * local_out)


def _conv_cost(eqn, specs, mesh_shape, cost):
    """conv_general_dilated pricing (reference prices every op —
    ``comp_op_cost.py``; attention needs no special case here: on the
    planning trace it lowers to dot_generals, which are priced above).

    FLOPs = 2 * out_elems * (Cin/groups) * kernel_volume, divided by
    every mesh axis sharding either operand.  An input-feature split is
    a contraction split -> ring all_reduce of the output.  Spatial
    shardings would need halo exchanges; they are not modeled (the
    planner never proposes them — batch/feature splits dominate on
    TPU), so their comm cost conservatively prices as a contraction.
    """
    lhs, rhs = eqn.invars[:2]
    out = eqn.outvars[0]
    dn = eqn.params["dimension_numbers"]
    groups = int(eqn.params.get("feature_group_count", 1))
    ls = specs.get(lhs) or (None,) * lhs.aval.ndim
    rs = specs.get(rhs) or (None,) * rhs.aval.ndim

    out_elems = math.prod(out.aval.shape) if out.aval.shape else 1
    cin = lhs.aval.shape[dn.lhs_spec[1]]
    kernel_vol = math.prod(rhs.aval.shape[i] for i in dn.rhs_spec[2:])
    total_flops = 2.0 * out_elems * (cin // max(groups, 1)) * kernel_vol

    sharding_axes = set()
    for e in tuple(ls) + tuple(rs):
        sharding_axes.update(_axes_of(e))
    nshard = _axes_size(sharding_axes, mesh_shape)
    cost.flops_per_device += total_flops / max(nshard, 1)

    # contraction axes: input-feature dim on either operand, and any
    # spatial sharding (halo-needing — priced as a reduce)
    contract_axes = set(_axes_of(ls[dn.lhs_spec[1]]))
    contract_axes.update(_axes_of(rs[dn.rhs_spec[1]]))
    for d in dn.lhs_spec[2:]:
        contract_axes.update(_axes_of(ls[d]))
    for d in dn.rhs_spec[2:]:
        # kernel-spatial weight splits also need halo/reduce traffic —
        # price them as contractions so the planner never "wins" by
        # sharding a kh/kw dim for free
        contract_axes.update(_axes_of(rs[d]))
    na = _axes_size(contract_axes, mesh_shape)
    if na > 1:
        out_bytes = out_elems * _dtype_size(out.aval)
        out_axes = {a for e in (specs.get(out) or ())
                    for a in _axes_of(e)} - contract_axes
        local_out = out_bytes / max(_axes_size(out_axes, mesh_shape), 1)
        cost._add_comm("all_reduce", eqn.primitive.name,
                       2 * (na - 1) / na * local_out)


def estimate_plan_cost(jaxpr, invar_specs: Sequence[Optional[tuple]],
                       mesh_shape: Dict[str, int],
                       param_count: int) -> PlanCost:
    """Cost of running ``jaxpr`` with the given invar placements: runs the
    completion propagation, then prices every matmul's collectives.
    ``param_count`` is the number of leading invars that are PARAMETERS
    (only those contribute HBM param-read bytes — inputs must not)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    # monotone merge converges in a few sweeps; 8 bounds planner trials
    specs = propagate_jaxpr_specs(jaxpr, invar_specs, max_iters=8)
    cost = PlanCost()

    n_params = param_count
    for v, s in zip(jaxpr.invars[:n_params], invar_specs):
        nbytes = math.prod(v.aval.shape or (1,)) * _dtype_size(v.aval)
        axes = {a for e in (s or ()) for a in _axes_of(e)}
        cost.param_bytes_per_device += nbytes / max(
            _axes_size(axes, mesh_shape), 1)

    def walk(j):
        for eqn in j.eqns:
            sub = _subjaxpr_of(eqn)
            if sub is not None:
                walk(sub.jaxpr if hasattr(sub, "jaxpr") else sub)
            elif eqn.primitive.name == "dot_general":
                _dot_cost(eqn, specs, mesh_shape, cost)
            elif eqn.primitive.name == "conv_general_dilated":
                _conv_cost(eqn, specs, mesh_shape, cost)

    walk(jaxpr)
    return cost


def choose_param_plan(jaxpr, params, base_specs, mesh, axis: str = "mp",
                      param_count: Optional[int] = None):
    """Greedy per-parameter plan selection (reference parallel_tuner's
    search, statically costed): for each 2D parameter without a user
    annotation, try {replicated, row-split, col-split} over ``axis`` given
    the placements already chosen, keep the cheapest.  Returns completed
    spec list aligned with ``params``.

    Cost: up to 3 full-jaxpr propagations per open 2D parameter (each a
    few monotone sweeps over the eqns) — pure-Python planning time grows
    with params x eqns, so this runs once at Engine.prepare, never per
    step."""
    mesh_shape = dict(mesh.shape)
    nax = mesh_shape.get(axis, 1)
    if nax <= 1:
        return list(base_specs)
    chosen = list(base_specs)
    for i, p in enumerate(params):
        if chosen[i] is not None:
            continue
        shape = p._value.shape if hasattr(p, "_value") else p.shape
        if len(shape) < 2:
            continue
        # candidates: replicated, plus a single-axis split on each dim
        # that divides evenly (covers Linear row/col, conv Cout/Cin and
        # stacked-expert leading dims)
        candidates = [None]
        for d, s in enumerate(shape):
            if s % nax == 0 and s >= nax:
                spec = [None] * len(shape)
                spec[d] = axis
                candidates.append(tuple(spec))
        if len(candidates) == 1:
            continue
        best, best_cost = None, None
        for cand in candidates:
            trial = list(chosen)
            trial[i] = cand
            c = estimate_plan_cost(jaxpr, trial, mesh_shape,
                                   param_count=param_count).total()
            # strict improvement required: ties keep replicated
            if best_cost is None or c < best_cost * (1 - 1e-9):
                best, best_cost = cand, c
        chosen[i] = best
    return chosen


_HLO_COLL = re.compile(
    # result text = everything between `=` and the op kind on the same
    # line (lazy; shape syntax never contains a kind name) — robust to
    # arbitrary tuple nesting and TPU tiled layouts like {0:T(8,128)}
    r"=\s*(?P<res>[^\n]*?)\s*"
    r"(?P<kind>all-reduce|all-gather|reduce-scatter|collective-permute"
    r"|all-to-all)"
    r"(?P<start>-start)?\(")

_HLO_SHAPE = re.compile(r"(\w+)\[([\d,]*)\]")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "s32": 4, "u32": 4, "bf16": 2,
                "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
                "s64": 8, "u64": 8}


def hlo_collective_bytes(hlo_text: str) -> Dict[str, float]:
    """Total bytes per collective kind parsed from HLO text — the ground
    truth the static estimate is validated against in tests.

    Tuple-shaped results (multi-operand collectives, e.g.
    ``= (f32[..], f32[..]) all-reduce(...)``) sum every element.  Async
    ``-start`` handling is per kind: all-reduce-start's tuple is all
    outputs (one per operand — counted whole), while all-gather /
    collective-permute / all-to-all -start tuples alias the input
    buffers in their first half (only the destination half counts),
    with u32 context scalars dropped by dtype (a scalar f32[] payload
    stays).  The matching ``-done`` op carries no shape of its own
    (it never matches because the kind must be followed by ``(``).
    """
    out: Dict[str, float] = {}
    for m in _HLO_COLL.finditer(hlo_text):
        res, kind = m.group("res"), m.group("kind")
        shapes = _HLO_SHAPE.findall(res)
        if m.group("start") and res.startswith("("):
            shapes = [s for s in shapes if not (s[0] == "u32" and not s[1])]
            if kind != "all-reduce" and len(shapes) >= 2:
                shapes = shapes[len(shapes) // 2:]
        nbytes = 0.0
        for dtype, dims in shapes:
            elems = math.prod(int(d) for d in dims.split(",") if d) \
                if dims else 1
            nbytes += elems * _DTYPE_BYTES.get(dtype, 4)
        out[kind] = out.get(kind, 0.0) + nbytes
    return out
