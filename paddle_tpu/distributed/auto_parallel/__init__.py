"""Auto-parallel API (analogue of python/paddle/distributed/auto_parallel/).

The dygraph semi-auto surface (shard_tensor/reshard/ProcessMesh) lives in
paddle_tpu.distributed.sharding_api; this package re-exports it and hosts the
static Engine analogue (strategy-driven compiled training).
"""

from ..sharding_api import (Partial, ProcessMesh, Replicate, Shard, reshard,
                            shard_layer, shard_optimizer, shard_tensor)
from .engine import Engine, Strategy

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "shard_layer", "shard_optimizer", "reshard", "Engine", "Strategy"]
