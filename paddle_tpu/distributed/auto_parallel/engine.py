"""Auto-parallel Engine (analogue of
python/paddle/distributed/auto_parallel/static/engine.py: Engine:55).

Reference pipeline: _build -> _plan (completion propagates dist_attrs) ->
_parallel (partitioner + reshard) -> exec.  TPU-native pipeline: the "plan"
is GSPMD — user annotations on a few tensors propagate through XLA's sharding
propagation pass; "partition + reshard" is the compiled SPMD program.  So the
Engine here: collects annotations, builds one compiled train step over the
mesh, and runs fit/evaluate/predict with the reference's API.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.tensor import Tensor


def _spec_is_valid(spec, shape, mesh):
    """A propagated spec is usable only if no mesh axis is reused across
    dims, every named axis exists on the mesh, and every sharded dim is
    divisible by the product of its axis sizes."""
    seen = set()
    for dim, axes in zip(shape, spec):
        if axes is None:
            continue
        axes = axes if isinstance(axes, (tuple, list)) else (axes,)
        size = 1
        for ax in axes:
            if ax in seen or ax not in mesh.shape:
                return False
            seen.add(ax)
            size *= mesh.shape[ax]
        if size == 0 or dim % size != 0:
            return False
    return True


class Strategy:
    """Analogue of auto_parallel Strategy (subset of switches)."""

    def __init__(self):
        class _Flag:
            enable = False

            def __init__(self):
                self.enable = False

        self.amp = _Flag()
        self.recompute = _Flag()
        self.sharding = _Flag()
        self.gradient_merge = _Flag()
        self.pipeline = _Flag()
        # cost-model plan SELECTION (reference parallel_tuner role):
        # when enabled, parameters the completion pass leaves unplaced are
        # assigned row/column/replicated splits by the static estimator
        self.auto_search = _Flag()


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._train_step = None
        self._completed = False

    def prepare(self, inputs_spec=None, labels_spec=None, mode="train",
                init_parameters=True):
        """Run the completion pass from the user's sparse annotations
        (reference Engine.prepare -> Planner/Completer).  ``inputs_spec``/
        ``labels_spec``: InputSpec-like objects (``.shape``/``.dtype``)
        or example Tensors used to trace the program."""
        import jax.numpy as jnp

        def example(spec):
            if spec is None:
                return None
            if isinstance(spec, Tensor):
                return spec
            if isinstance(spec, (list, tuple)):
                spec = spec[0]
            if isinstance(spec, Tensor):
                return spec
            shape = [1 if (d is None or d == -1) else d for d in spec.shape]
            dtype = getattr(spec, "dtype", "float32")
            if "int" in str(dtype):
                return Tensor(jnp.zeros(shape, jnp.int32))
            return Tensor(jnp.zeros(shape, jnp.float32))

        x = example(inputs_spec)
        y = example(labels_spec)
        if x is not None:
            self._complete(x, y)
        return self

    def _complete(self, x, y):
        """Propagate shardings from annotated tensors to every parameter
        (completion.py); place completed params on the mesh."""
        if self._completed:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from ..topology import get_global_mesh
        from ...core.tape import no_grad

        mesh = get_global_mesh()
        if mesh is None:
            return
        params = [p for p in self._model.parameters() if not p.stop_gradient]
        annotated = [p for p in params if p._dist_attr is not None]
        input_annotated = getattr(x, "_dist_attr", None) is not None or \
            (y is not None and getattr(y, "_dist_attr", None) is not None)
        auto_on = bool(getattr(self._strategy, "auto_search", None)
                       and self._strategy.auto_search.enable)
        if not annotated and not input_annotated and not auto_on:
            return

        model, loss = self._model, self._loss

        def fn(pv, xa, *ya):
            saved = [p._value for p in params]
            try:
                for p, a in zip(params, pv):
                    p._value = a
                with no_grad():
                    out = model(Tensor(xa))
                    if loss is not None and ya:
                        out = loss(out, Tensor(ya[0]))
                return out._value if isinstance(out, Tensor) else out
            finally:
                for p, s in zip(params, saved):
                    p._value = s

        inputs = [x] if y is None else [x, y]
        try:
            from .completion import trace_and_complete
            jaxpr, invar_specs, specs = trace_and_complete(fn, params,
                                                           inputs)
        except Exception:
            # completion is best-effort (GSPMD defaults still work) — but
            # mark it done so fit() doesn't re-trace the model every batch
            self._completed = True
            return
        if auto_on and any(s is None for s in specs):
            try:
                from .cost_model import choose_param_plan
                # seed the search with whatever completion inferred, plus
                # the annotated input specs at the tail
                base = list(specs) + list(invar_specs[len(params):])
                plan_axis = next(
                    (a for a in ("mp", "model") if mesh.shape.get(a, 1) > 1),
                    None)
                if plan_axis is not None:
                    planned = choose_param_plan(
                        jaxpr, params, base, mesh, axis=plan_axis,
                        param_count=len(params))
                    specs = planned[:len(params)]
            except Exception:
                pass  # planning is best-effort on top of completion
        for p, s in zip(params, specs):
            if s is None or p._dist_attr is not None:
                continue
            if not any(e is not None for e in s):
                continue
            if not _spec_is_valid(s, p.shape, mesh):
                continue
            if isinstance(p._value, jax.core.Tracer):
                p._dist_attr = tuple(s)
                continue
            try:
                p._value = jax.device_put(
                    p._value, NamedSharding(mesh, PartitionSpec(*s)))
            except Exception:
                continue  # rejected placement must not leave a stale attr
            p._dist_attr = tuple(s)
        self._completed = True

    def _ensure_step(self):
        if self._train_step is None:
            from ...jit.train_step import TrainStep

            def loss_fn(net, x, y):
                out = net(x)
                return self._loss(out, y)

            step = TrainStep(self._model, loss_fn, self._optimizer)
            self._train_step = step if step._update_fn is not None else False

    def fit(self, train_data, train_sample_split=None, batch_size=1, epochs=1,
            steps_per_epoch=None, log_freq=10, valid_data=None, **kwargs):
        from ...io import DataLoader
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        history = []
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if isinstance(batch, (list, tuple)):
                    x, y = batch[0], batch[-1]
                else:
                    x, y = batch, None
                if not self._completed:
                    self._complete(x, y)
                self._ensure_step()
                if self._train_step:
                    loss = self._train_step(x, y)
                else:
                    self._model.train()
                    out = self._model(x)
                    loss = self._loss(out, y)
                    loss.backward()
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                if steps_per_epoch and step + 1 >= steps_per_epoch:
                    break
            history.append(float(np.asarray(loss._value)))
        return history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.tape import no_grad
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        losses = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(loader):
                x, y = (batch[0], batch[-1]) if isinstance(batch, (list, tuple)) \
                    else (batch, None)
                out = self._model(x)
                losses.append(float(np.asarray(self._loss(out, y)._value)))
                if steps and i + 1 >= steps:
                    break
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.tape import no_grad
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(loader):
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self._model(x))
                if steps and i + 1 >= steps:
                    break
        return outs

    def save(self, path, training=True):
        from ...framework.io import save as fsave
        fsave(self._model.state_dict(), path + ".pdparams")

    def load(self, path, strict=True, load_optimizer=True):
        from ...framework.io import load as fload
        self._model.set_state_dict(fload(path + ".pdparams"))
