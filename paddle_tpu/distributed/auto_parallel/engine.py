"""Auto-parallel Engine (analogue of
python/paddle/distributed/auto_parallel/static/engine.py: Engine:55).

Reference pipeline: _build -> _plan (completion propagates dist_attrs) ->
_parallel (partitioner + reshard) -> exec.  TPU-native pipeline: the "plan"
is GSPMD — user annotations on a few tensors propagate through XLA's sharding
propagation pass; "partition + reshard" is the compiled SPMD program.  So the
Engine here: collects annotations, builds one compiled train step over the
mesh, and runs fit/evaluate/predict with the reference's API.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.tensor import Tensor


class Strategy:
    """Analogue of auto_parallel Strategy (subset of switches)."""

    def __init__(self):
        class _Flag:
            enable = False

            def __init__(self):
                self.enable = False

        self.amp = _Flag()
        self.recompute = _Flag()
        self.sharding = _Flag()
        self.gradient_merge = _Flag()
        self.pipeline = _Flag()


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._train_step = None

    def _ensure_step(self):
        if self._train_step is None:
            from ...jit.train_step import TrainStep

            def loss_fn(net, x, y):
                out = net(x)
                return self._loss(out, y)

            step = TrainStep(self._model, loss_fn, self._optimizer)
            self._train_step = step if step._update_fn is not None else False

    def fit(self, train_data, train_sample_split=None, batch_size=1, epochs=1,
            steps_per_epoch=None, log_freq=10, valid_data=None, **kwargs):
        from ...io import DataLoader
        loader = train_data if isinstance(train_data, DataLoader) else \
            DataLoader(train_data, batch_size=batch_size, shuffle=True)
        self._ensure_step()
        history = []
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if isinstance(batch, (list, tuple)):
                    x, y = batch[0], batch[-1]
                else:
                    x, y = batch, None
                if self._train_step:
                    loss = self._train_step(x, y)
                else:
                    self._model.train()
                    out = self._model(x)
                    loss = self._loss(out, y)
                    loss.backward()
                    self._optimizer.step()
                    self._optimizer.clear_grad()
                if steps_per_epoch and step + 1 >= steps_per_epoch:
                    break
            history.append(float(np.asarray(loss._value)))
        return history

    def evaluate(self, valid_data, valid_sample_split=None, batch_size=1,
                 steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.tape import no_grad
        loader = valid_data if isinstance(valid_data, DataLoader) else \
            DataLoader(valid_data, batch_size=batch_size)
        losses = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(loader):
                x, y = (batch[0], batch[-1]) if isinstance(batch, (list, tuple)) \
                    else (batch, None)
                out = self._model(x)
                losses.append(float(np.asarray(self._loss(out, y)._value)))
                if steps and i + 1 >= steps:
                    break
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, test_sample_split=None, batch_size=1,
                steps=None, **kwargs):
        from ...io import DataLoader
        from ...core.tape import no_grad
        loader = test_data if isinstance(test_data, DataLoader) else \
            DataLoader(test_data, batch_size=batch_size)
        outs = []
        self._model.eval()
        with no_grad():
            for i, batch in enumerate(loader):
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                outs.append(self._model(x))
                if steps and i + 1 >= steps:
                    break
        return outs

    def save(self, path, training=True):
        from ...framework.io import save as fsave
        fsave(self._model.state_dict(), path + ".pdparams")

    def load(self, path, strict=True, load_optimizer=True):
        from ...framework.io import load as fload
        self._model.set_state_dict(fload(path + ".pdparams"))
