"""Sharding completion pass (analogue of
``python/paddle/distributed/auto_parallel/static/completion.py``, 1,880 LoC
of dist-attr propagation rules).

TPU-native formulation: the user annotates a FEW tensors (inputs and one
or two weights, via ``shard_tensor``); this pass traces the training
function to a jaxpr and propagates PartitionSpecs through per-primitive
rules until a fixed point, then returns completed specs for every
parameter.  GSPMD handles intermediate activations at compile time — the
pass's job is to place the *parameters* consistently so XLA's propagation
never has to guess (the source of involuntary-rematerialization
reshards).

The key inference rule is bidirectional ``dot_general`` (the Megatron
pattern): if an activation arrives with its contraction dim sharded over
an axis, the matching weight dim gets that axis; if a weight's free dim
is sharded, the activation/output inherit it.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.extend import core as jcore

__all__ = ["complete_param_specs", "propagate_jaxpr_specs"]


Spec = Tuple  # tuple of (None | str | tuple[str, ...]) per dim


def _merge_entry(a, b):
    """Merge two dim entries; annotated (non-None) wins, first wins ties."""
    if a is None:
        return b
    return a


def _merge_spec(old: Optional[Spec], new: Optional[Spec]) -> Optional[Spec]:
    if new is None:
        return old
    if old is None:
        return tuple(new)
    if len(old) != len(new):
        return old
    return tuple(_merge_entry(a, b) for a, b in zip(old, new))


class _SpecEnv:
    def __init__(self):
        self.specs: Dict[jcore.Var, Spec] = {}
        self.changed = False

    def get(self, v) -> Optional[Spec]:
        if isinstance(v, jcore.Literal):
            return None
        return self.specs.get(v)

    def set(self, v, spec: Optional[Spec]):
        if spec is None or isinstance(v, jcore.Literal):
            return
        if not any(e is not None for e in spec):
            return
        aval = v.aval
        if len(spec) != getattr(aval, "ndim", -1):
            return
        merged = _merge_spec(self.specs.get(v), spec)
        if merged != self.specs.get(v):
            self.specs[v] = merged
            self.changed = True


def _dot_general_rule(eqn, env):
    lhs, rhs = eqn.invars
    out = eqn.outvars[0]
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    l_ndim = lhs.aval.ndim
    r_ndim = rhs.aval.ndim
    l_free = [d for d in range(l_ndim) if d not in lc and d not in lb]
    r_free = [d for d in range(r_ndim) if d not in rc and d not in rb]

    ls, rs, os = env.get(lhs), env.get(rhs), env.get(out)

    # forward: out = [batch..., lhs_free..., rhs_free...]
    out_spec = [None] * out.aval.ndim
    pos = 0
    for i, (db_l, db_r) in enumerate(zip(lb, rb)):
        if ls is not None:
            out_spec[pos] = _merge_entry(out_spec[pos], ls[db_l])
        if rs is not None:
            out_spec[pos] = _merge_entry(out_spec[pos], rs[db_r])
        pos += 1
    for d in l_free:
        if ls is not None:
            out_spec[pos] = ls[d]
        pos += 1
    for d in r_free:
        if rs is not None:
            out_spec[pos] = rs[d]
        pos += 1
    env.set(out, tuple(out_spec))

    # backward into rhs: contraction dims take lhs's contraction sharding;
    # free dims take the output's
    rhs_spec = [None] * r_ndim
    for cl, cr in zip(lc, rc):
        if ls is not None:
            rhs_spec[cr] = ls[cl]
    if os is not None:
        base = len(lb) + len(l_free)
        for k, d in enumerate(r_free):
            rhs_spec[d] = os[base + k]
    for i, (db_l, db_r) in enumerate(zip(lb, rb)):
        if os is not None:
            rhs_spec[db_r] = os[i]
    env.set(rhs, tuple(rhs_spec))

    # backward into lhs (symmetric)
    lhs_spec = [None] * l_ndim
    for cl, cr in zip(lc, rc):
        if rs is not None:
            lhs_spec[cl] = rs[cr]
    if os is not None:
        base = len(lb)
        for k, d in enumerate(l_free):
            lhs_spec[d] = os[base + k]
    for i, (db_l, db_r) in enumerate(zip(lb, rb)):
        if os is not None:
            lhs_spec[db_l] = os[i]
    env.set(lhs, tuple(lhs_spec))


def _transpose_rule(eqn, env):
    (x,), (out,) = eqn.invars, eqn.outvars
    perm = eqn.params["permutation"]
    xs = env.get(x)
    if xs is not None:
        env.set(out, tuple(xs[p] for p in perm))
    os = env.get(out)
    if os is not None:
        inv = [0] * len(perm)
        for i, p in enumerate(perm):
            inv[p] = i
        env.set(x, tuple(os[inv[d]] for d in range(len(perm))))


def _reshape_dim_map(src_shape, dst_shape):
    """Map src dims -> dst dims when every dim survives as a whole factor
    (merging/splitting of size-1 dims and clean prefix matches).  Returns
    dict src_dim -> dst_dim or None when ambiguous."""
    mapping = {}
    i = j = 0
    while i < len(src_shape) and j < len(dst_shape):
        if src_shape[i] == dst_shape[j]:
            mapping[i] = j
            i += 1
            j += 1
        elif src_shape[i] == 1:
            i += 1
        elif dst_shape[j] == 1:
            j += 1
        else:
            return None  # genuine split/merge: stop propagation
    return mapping


def _reshape_rule(eqn, env):
    (x,), (out,) = eqn.invars[:1], eqn.outvars
    m = _reshape_dim_map(x.aval.shape, out.aval.shape)
    if m is None:
        return
    xs = env.get(x)
    if xs is not None:
        spec = [None] * out.aval.ndim
        for s, d in m.items():
            spec[d] = xs[s]
        env.set(out, tuple(spec))
    os = env.get(out)
    if os is not None:
        spec = [None] * x.aval.ndim
        for s, d in m.items():
            spec[s] = os[d]
        env.set(x, tuple(spec))


def _broadcast_rule(eqn, env):
    (x,), (out,) = eqn.invars, eqn.outvars
    dims = eqn.params["broadcast_dimensions"]
    xs = env.get(x)
    if xs is not None:
        spec = [None] * out.aval.ndim
        for s, d in enumerate(dims):
            if x.aval.shape[s] == out.aval.shape[d]:
                spec[d] = xs[s]
        env.set(out, tuple(spec))
    os = env.get(out)
    if os is not None:
        spec = [None] * x.aval.ndim
        for s, d in enumerate(dims):
            if x.aval.shape[s] == out.aval.shape[d]:
                spec[s] = os[d]
        env.set(x, tuple(spec))


def _reduce_rule(eqn, env):
    (x,), (out,) = eqn.invars[:1], eqn.outvars
    axes = eqn.params.get("axes")
    if axes is None:
        return
    xs = env.get(x)
    if xs is not None:
        env.set(out, tuple(e for d, e in enumerate(xs) if d not in axes))
    os = env.get(out)
    if os is not None:
        spec = []
        it = iter(os)
        for d in range(x.aval.ndim):
            spec.append(None if d in axes else next(it))
        env.set(x, tuple(spec))


def _elementwise_rule(eqn, env):
    outs = eqn.outvars
    if not outs:
        return
    out = outs[0]
    shape = getattr(out.aval, "shape", None)
    if shape is None:
        return
    # same-shape peers share the full spec
    peers = [v for v in list(eqn.invars) + [out]
             if getattr(v.aval, "shape", None) == shape]
    best = None
    for v in peers:
        best = _merge_spec(best, env.get(v))
    if best is not None:
        for v in peers:
            env.set(v, best)
    # broadcast-compatible operands (same ndim, dims equal or 1): share
    # per-dim entries on the non-broadcast dims — this is how a bias
    # vector inherits its layer's column sharding through the add
    if best is None:
        return
    for v in eqn.invars:
        vshape = getattr(v.aval, "shape", None)
        if vshape is None or vshape == shape or len(vshape) != len(shape):
            continue
        if not all(a == b or a == 1 for a, b in zip(vshape, shape)):
            continue
        env.set(v, tuple(None if a == 1 else e
                         for a, e in zip(vshape, best)))


def _subjaxpr_of(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None:
            return sub
    return None


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "pow", "tanh", "exp", "log",
    "logistic", "rsqrt", "sqrt", "neg", "abs", "sign", "select_n",
    "integer_pow", "convert_element_type", "stop_gradient", "copy",
    "erf", "sin", "cos", "and", "or", "xor", "not", "eq", "ne", "lt", "le",
    "gt", "ge", "where", "clamp", "square",
}


def propagate_jaxpr_specs(jaxpr: jcore.Jaxpr,
                          invar_specs: Sequence[Optional[Spec]],
                          max_iters: int = 32) -> Dict[jcore.Var, Spec]:
    """Fixed-point propagation over one jaxpr; returns specs for all vars
    (invars included — the completed parameter placements)."""
    env = _SpecEnv()
    for v, s in zip(jaxpr.invars, invar_specs):
        if s is not None:
            env.set(v, tuple(s))

    def run_eqn(eqn):
        prim = eqn.primitive.name
        sub = _subjaxpr_of(eqn)
        if sub is not None:
            inner = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            n = min(len(inner.invars), len(eqn.invars))
            for outer, v_in in zip(eqn.invars[:n], inner.invars[:n]):
                s = env.get(outer)
                if s is not None:
                    env.set(v_in, s)
            for ie in inner.eqns:
                run_eqn(ie)
            for outer, v_out in zip(eqn.outvars, inner.outvars):
                s = env.get(v_out) if not isinstance(v_out, jcore.Literal) \
                    else None
                if s is not None:
                    env.set(outer, s)
                so = env.get(outer)
                if so is not None and not isinstance(v_out, jcore.Literal):
                    env.set(v_out, so)
            # let outer->inner invar info flow back out too
            for outer, v_in in zip(eqn.invars[:n], inner.invars[:n]):
                s = env.get(v_in)
                if s is not None:
                    env.set(outer, s)
            return
        if prim == "dot_general":
            _dot_general_rule(eqn, env)
        elif prim == "transpose":
            _transpose_rule(eqn, env)
        elif prim == "reshape":
            _reshape_rule(eqn, env)
        elif prim == "broadcast_in_dim":
            _broadcast_rule(eqn, env)
        elif prim in ("reduce_sum", "reduce_max", "reduce_min",
                      "reduce_prod", "argmax", "argmin"):
            _reduce_rule(eqn, env)
        elif prim in _ELEMENTWISE:
            _elementwise_rule(eqn, env)
        # unknown primitives: no rule — propagation stops there (safe)

    for _ in range(max_iters):
        env.changed = False
        for eqn in jaxpr.eqns:
            run_eqn(eqn)
        if not env.changed:
            break
    return env.specs


def trace_and_complete(fn, params, example_inputs):
    """Trace ``fn(param_arrays, *input_arrays)`` and run completion.
    Returns ``(jaxpr, invar_specs, completed_param_specs)`` — the jaxpr
    and annotation-aligned invar specs feed the cost model's plan search
    (cost_model.choose_param_plan)."""
    from ...core.tensor import Tensor

    p_arrays = [p._value for p in params]
    in_arrays = [x._value if isinstance(x, Tensor) else np.asarray(x)
                 for x in example_inputs]
    closed = jax.make_jaxpr(
        lambda pv, *xs: fn(pv, *xs))(p_arrays, *in_arrays)
    jaxpr = closed.jaxpr

    invar_specs = []
    for p in params:
        invar_specs.append(tuple(p._dist_attr)
                           if p._dist_attr is not None else None)
    for x in example_inputs:
        spec = getattr(x, "_dist_attr", None)
        invar_specs.append(tuple(spec) if spec is not None else None)
    specs = propagate_jaxpr_specs(jaxpr, invar_specs)

    out = []
    for v in jaxpr.invars[:len(params)]:
        s = specs.get(v)
        out.append(s if s is not None and any(e is not None for e in s)
                   else None)
    return jaxpr, invar_specs, out


def complete_param_specs(fn, params, example_inputs, mesh=None):
    """Trace ``fn(param_arrays, *input_arrays)`` and complete parameter
    specs from the sparse annotations found on ``params`` (Tensor
    ``_dist_attr``) and on the example inputs.

    Returns a list of PartitionSpec-compatible tuples aligned with
    ``params`` (None where nothing was inferred).
    """
    return trace_and_complete(fn, params, example_inputs)[2]
