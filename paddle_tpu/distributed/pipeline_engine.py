"""SPMD pipeline-parallel engine.

The TPU-native replacement for the reference's pipeline runtimes — both the
dygraph 1F1B loop (``fleet/meta_parallel/pipeline_parallel.py:387``
forward_backward_pipeline + p2p_communication.py NCCL send/recv) and the
static FleetExecutor actor graph (``fleet_executor/carrier.h:50`` +
interceptors).  Design (scaling-book collective-permute pipelining):

- The pipeline is expressed as ONE differentiable program: a ``lax.scan``
  over schedule ticks inside a ``shard_map`` that is *manual* over the
  "pipe" mesh axis and *auto* (GSPMD) over data/model/sharding/sep axes —
  so TP/DP compose freely inside each stage.
- Micro-batch activations move between stages with ``lax.ppermute``
  (collective-permute rides ICI); XLA overlaps the permute of tick t with
  the compute of tick t+1 — the steady-state overlap the reference builds
  with P2P threads comes from the compiler schedule.
- ``jax.grad`` through the scan+ppermute yields the backward pipeline
  automatically (reversed scan, transposed permutes): a GPipe schedule —
  simple and fully differentiable, but its stashed activations scale with
  n_microbatches.
- ``pipeline_train_step_1f1b`` is the memory-bounded training schedule
  (reference 1F1B, ``pipeline_parallel.py:387``): one scan whose ticks
  each run a forward unit AND a backward unit (explicit per-tick
  ``jax.vjp``, residuals never cross ticks), with a statically simulated
  per-rank schedule and an O(pp) circular stash — in-flight activations
  are bounded by the pipeline depth, not the microbatch count.

Stages must be shape-homogeneous (stage_fn: (stage_params, x) -> y with y
shaped like x) — the transformer-decoder case; embedding/head run outside
the pipelined region (the reference's PipelineLayer shares them across
first/last stages for the same reason, pp_layers.py SharedLayerDesc).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PIPE_AXIS = "pipe"



from .topology import pvary as _pvary


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> single pytree with a leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def shard_stacked_params(stacked, mesh: Mesh):
    """Place stacked stage params with the stage dim over the pipe axis."""
    def place(leaf):
        spec = PartitionSpec(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, micro_xs,
                   n_stages: int, mesh: Mesh,
                   remat: bool = True):
    """Run micro-batches through the stage pipeline.

    stage_fn(stage_params, x) -> y (same shape as x).
    stacked_params: pytree, leaves [n_stages, ...] (sharded over pipe).
    micro_xs: [n_micro, micro_batch, ...] activations entering stage 0.
    Returns ys: [n_micro, micro_batch, ...] — the last stage's outputs,
    valid on every device (broadcast over the pipe axis).
    """
    n_micro = micro_xs.shape[0]
    total_ticks = n_micro + n_stages - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def inner(params, xs):
        # inside shard_map: params leaves have leading dim 1 (this stage)
        my_params = jax.tree_util.tree_map(lambda l: l[0], params)
        stage_idx = jax.lax.axis_index(PIPE_AXIS)
        is_first = stage_idx == 0
        is_last = stage_idx == n_stages - 1

        buf0 = jnp.zeros_like(xs[0])
        # mark the carry as varying over the pipe axis (shard_map VMA typing):
        # the replicated zero init becomes device-varying after the first
        # ppermute, so the scan carry type must start varying.
        buf0 = _pvary(buf0, (PIPE_AXIS,))

        def tick(carry, t):
            recv = carry
            # stage 0 feeds microbatch t (clamped); others take the wire
            mb = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(is_first, mb, recv)
            out = fn(my_params, inp)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, buf0, jnp.arange(total_ticks))
        # last stage produced valid results at ticks S-1 .. T-1
        ys_last = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro,
                                               axis=0)
        # broadcast last stage's outputs to all pipe ranks (psum of masked)
        contrib = jnp.where(is_last, ys_last, jnp.zeros_like(ys_last))
        return jax.lax.psum(contrib, PIPE_AXIS)

    n_dims_x = micro_xs.ndim
    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: PartitionSpec(PIPE_AXIS),
                                   stacked_params),
            PartitionSpec(*([None] * n_dims_x)),
        ),
        out_specs=PartitionSpec(*([None] * n_dims_x)),
        axis_names={PIPE_AXIS},
    )
    return sm(stacked_params, micro_xs)


class PipelineStageRunner:
    """Convenience wrapper binding stage_fn + mesh for repeated use."""

    def __init__(self, stage_fn, n_stages, mesh, remat=True):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.mesh = mesh
        self.remat = remat

    def __call__(self, stacked_params, micro_xs):
        return pipeline_apply(self.stage_fn, stacked_params, micro_xs,
                              self.n_stages, self.mesh, self.remat)


def simulate_1f1b_schedule(n_stages: int, n_micro: int):
    """Statically simulate the 1F1B schedule (reference
    ``PipelineParallel._forward_backward_pipeline``'s warmup/steady/
    cooldown phases, ``pipeline_parallel.py:387``).

    Each tick, a rank may run one forward unit and one backward unit.
    Rank r stashes at most ``2(S - r) - 1`` microbatch inputs: with both
    units sharing a tick, a microbatch's cotangent returns 2(S - 1 - r)
    ticks after its forward, so this admission cap (not the sequential
    1F1B ``S - r``) is what sustains one microbatch per tick while keeping
    the stash O(pipeline depth), constant in n_micro.  Backwards fire as
    soon as the cotangent arrived (last rank: same tick as its forward).

    Returns int32 numpy arrays ``(fwd_m, bwd_m, fwd_in, bwd_in)`` of shape
    [T, S]: the microbatch forwarded/backwarded by rank r at tick t (-1 =
    idle), and the microbatch whose activation/cotangent ARRIVES on the
    wire at tick t (sent at t-1 by the neighbor).
    """
    import numpy as np

    S, M = n_stages, n_micro
    fwd_tick = [[-1] * M for _ in range(S)]
    bwd_tick = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    fwd_sched, bwd_sched = [], []
    t = 0
    while any(nb < M for nb in next_b):
        if t > 4 * (M + S) + 8:  # schedule must close; bug otherwise
            raise RuntimeError("1F1B schedule did not converge")
        fs, bs = [-1] * S, [-1] * S
        for r in range(S):
            m = next_f[r]
            cap = max(1, 2 * (S - r) - 1)
            if m < M and (m - next_b[r]) < cap and \
                    (r == 0 or (fwd_tick[r - 1][m] >= 0
                                and fwd_tick[r - 1][m] < t)):
                fs[r] = m
                fwd_tick[r][m] = t
                next_f[r] += 1
        for r in range(S - 1, -1, -1):
            m = next_b[r]
            if m >= M:
                continue
            if r == S - 1:
                ready = fwd_tick[r][m] >= 0 and fwd_tick[r][m] <= t
            else:
                ready = bwd_tick[r + 1][m] >= 0 and bwd_tick[r + 1][m] < t
            if ready:
                bs[r] = m
                bwd_tick[r][m] = t
                next_b[r] += 1
        fwd_sched.append(fs)
        bwd_sched.append(bs)
        t += 1

    T = len(fwd_sched)
    fwd_m = np.asarray(fwd_sched, np.int32)
    bwd_m = np.asarray(bwd_sched, np.int32)
    fwd_in = np.full((T, S), -1, np.int32)
    bwd_in = np.full((T, S), -1, np.int32)
    for tt in range(1, T):
        for r in range(S):
            if r > 0 and fwd_m[tt - 1, r - 1] >= 0:
                fwd_in[tt, r] = fwd_m[tt - 1, r - 1]
            if r < S - 1 and bwd_m[tt - 1, r + 1] >= 0:
                bwd_in[tt, r] = bwd_m[tt - 1, r + 1]
    return fwd_m, bwd_m, fwd_in, bwd_in


def pipeline_train_step_1f1b(stage_fn: Callable, loss_fn: Callable,
                             stacked_params: Any, micro_xs, micro_labels,
                             n_stages: int, mesh: Mesh,
                             remat: bool = True):
    """1F1B pipeline training step: returns ``(mean_loss, param_grads)``
    with per-device in-flight activations bounded by the pipeline depth.

    stage_fn(stage_params, x) -> y (same shape as x);
    loss_fn(y, label_mb) -> scalar (evaluated on the LAST stage's output).
    stacked_params: pytree, leaves [n_stages, ...]; micro_xs
    [n_micro, micro, ...]; micro_labels [n_micro, ...] aligned with xs.
    param_grads come back stacked like ``stacked_params``.

    Memory: the scan carries circular [2S, micro, ...] stash/wire
    buffers (2S slots because up to 2(S-r)-1 microbatches are in flight
    per rank) — constant in n_micro.  What stays O(batch) is the INPUT
    feed: ``micro_xs``/``micro_labels`` are replicated to every pipe rank
    (only rank 0 reads xs, rank S-1 reads labels) — that is the caller's
    batch, present in any trainer pipelined or not, and it is argument
    memory, not the schedule's stashed-activation term this engine bounds.  The
    backward unit re-runs the stage forward inside ``jax.vjp`` each tick
    (recompute-in-1F1B, the reference's recompute interval), so residuals
    never cross scan ticks.
    """
    S = n_stages
    n_micro = micro_xs.shape[0]
    fwd_m, bwd_m, fwd_in, bwd_in = simulate_1f1b_schedule(S, n_micro)
    total_ticks = fwd_m.shape[0]
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    perm_f = [(i, (i + 1) % S) for i in range(S)]
    perm_b = [(i, (i - 1) % S) for i in range(S)]

    f_m = jnp.asarray(fwd_m)
    b_m = jnp.asarray(bwd_m)
    f_in = jnp.asarray(fwd_in)
    b_in = jnp.asarray(bwd_in)

    def inner(params, xs, labels):
        my_params = jax.tree_util.tree_map(lambda l: l[0], params)
        r = jax.lax.axis_index(PIPE_AXIS)
        is_first = r == 0
        is_last = r == S - 1

        zero_mb = jnp.zeros_like(xs[0])
        n_slots = 2 * S
        stash0 = _pvary(jnp.zeros((n_slots,) + xs.shape[1:], xs.dtype),
                        (PIPE_AXIS,))
        wire_a0 = _pvary(zero_mb, (PIPE_AXIS,))
        wire_c0 = _pvary(zero_mb, (PIPE_AXIS,))
        grads0 = jax.tree_util.tree_map(
            lambda l: _pvary(jnp.zeros_like(l[0]), (PIPE_AXIS,)), params)
        loss0 = _pvary(jnp.zeros((), jnp.float32), (PIPE_AXIS,))

        def sched(tab, t):
            row = jax.lax.dynamic_index_in_dim(tab, t, axis=0,
                                               keepdims=False)
            return jax.lax.dynamic_index_in_dim(row, r, axis=0,
                                                keepdims=False)

        def tick(carry, t):
            wire_a, wire_c, in_acts, in_cots, stash, grads, loss = carry
            fm = sched(f_m, t)
            bm = sched(b_m, t)
            fin = sched(f_in, t)
            bin_ = sched(b_in, t)

            # deliver last tick's wire traffic into the circular buffers
            in_acts = jnp.where(
                fin >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    in_acts, wire_a, jnp.maximum(fin, 0) % n_slots, axis=0),
                in_acts)
            in_cots = jnp.where(
                bin_ >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    in_cots, wire_c, jnp.maximum(bin_, 0) % n_slots, axis=0),
                in_cots)

            # ---- forward unit ----
            fm_c = jnp.maximum(fm, 0)
            x_local = jax.lax.dynamic_index_in_dim(xs, fm_c, axis=0,
                                                   keepdims=False)
            x_wire = jax.lax.dynamic_index_in_dim(in_acts, fm_c % n_slots,
                                              axis=0,
                                                  keepdims=False)
            x_in = jnp.where(is_first, x_local, x_wire)
            out_f = fn(my_params, x_in)
            stash = jnp.where(
                fm >= 0,
                jax.lax.dynamic_update_index_in_dim(
                    stash, x_in, fm_c % n_slots, axis=0),
                stash)

            # ---- backward unit (explicit vjp; residuals die with the
            # tick — this is the 1F1B recompute) ----
            bm_c = jnp.maximum(bm, 0)
            x_saved = jax.lax.dynamic_index_in_dim(stash, bm_c % n_slots,
                                               axis=0,
                                                   keepdims=False)
            y, vjp_fn = jax.vjp(fn, my_params, x_saved)
            label_mb = jax.lax.dynamic_index_in_dim(labels, bm_c, axis=0,
                                                    keepdims=False)
            loss_m, dy_loss = jax.value_and_grad(loss_fn)(y, label_mb)
            cot_wire = jax.lax.dynamic_index_in_dim(in_cots, bm_c % n_slots,
                                                    axis=0, keepdims=False)
            cot = jnp.where(is_last, dy_loss, cot_wire)
            dp, dx = vjp_fn(cot)
            live = bm >= 0
            grads = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(live, d, jnp.zeros_like(d)),
                grads, dp)
            loss = loss + jnp.where(live & is_last, loss_m, 0.0)

            # ---- wires for next tick ----
            wire_a = jax.lax.ppermute(out_f, PIPE_AXIS, perm_f)
            wire_c = jax.lax.ppermute(dx, PIPE_AXIS, perm_b)
            return (wire_a, wire_c, in_acts, in_cots, stash, grads,
                    loss), None

        carry0 = (wire_a0, wire_c0, stash0, stash0, stash0, grads0, loss0)
        (_, _, _, _, _, grads, loss), _ = jax.lax.scan(
            tick, carry0, jnp.arange(total_ticks))
        # loss lives on the last rank; grads live per rank — return the
        # microbatch-MEAN loss and matching grads, stacked over pipe
        loss_all = jax.lax.psum(loss, PIPE_AXIS) / n_micro
        grads_out = jax.tree_util.tree_map(lambda g: g[None] / n_micro,
                                           grads)
        return loss_all, grads_out

    n_dims_x = micro_xs.ndim
    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: PartitionSpec(PIPE_AXIS),
                                   stacked_params),
            PartitionSpec(*([None] * n_dims_x)),
            PartitionSpec(*([None] * micro_labels.ndim)),
        ),
        out_specs=(
            PartitionSpec(),
            jax.tree_util.tree_map(lambda _: PartitionSpec(PIPE_AXIS),
                                   stacked_params),
        ),
        axis_names={PIPE_AXIS},
    )
    return sm(stacked_params, micro_xs, micro_labels)


def pipeline_apply_interleaved(stage_fn: Callable, stacked_params: Any,
                               micro_xs, n_stages: int, n_chunks: int,
                               mesh: Mesh, remat: bool = True):
    """Interleaved (virtual-stage) pipeline schedule.

    The analogue of the reference's PipelineParallelWithInterleave
    (``fleet/meta_parallel/pipeline_parallel.py:822`` + interleaved
    segmentation in ``pp_layers.py``): each pipe rank holds ``n_chunks``
    virtual stages; global stage ``g = c * n_stages + r`` lives on rank
    ``r`` as chunk ``c``, so a microbatch traverses the ring v times.
    Bubble shrinks from (S-1)/(S-1+M) to (S-1)/(S-1+M*v) schedule units.

    Schedule (Megatron-style unit ordering, n_micro padded to a multiple
    of S): unit ``u`` = microbatch ``m = (u // (S*v))*S + u % S`` at chunk
    ``c = (u // S) % v``; rank r executes unit ``t - r`` at tick t.  The
    unit leaving rank S-1 (chunk c) arrives at rank 0 exactly when chunk
    c+1 of that microbatch is scheduled, so the same wrap-around ppermute
    wire as the GPipe schedule carries all chunk transitions.

    stacked_params: pytree with leaves [n_chunks * n_stages, ...] ordered
    by global stage (stack_stage_params over the g = 0..S*v-1 chain); this
    function reshapes to [v, S, ...] and shards the rank axis over pipe
    itself.  micro_xs: [n_micro, micro, ...].
    """
    S, v = n_stages, n_chunks
    n_micro = micro_xs.shape[0]
    pad = (-n_micro) % S
    if pad:
        micro_xs = jnp.concatenate(
            [micro_xs, jnp.zeros((pad,) + micro_xs.shape[1:],
                                 micro_xs.dtype)], axis=0)
    m_total = n_micro + pad
    n_units = m_total * v
    total_ticks = n_units + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    perm = [(i, (i + 1) % S) for i in range(S)]

    def inner(params, xs):
        # params leaves arrive [v, 1, ...] (global [v, S, ...] split on
        # axis 1 = rank); squeeze to [v, ...] = this rank's chunks
        my_chunks = jax.tree_util.tree_map(lambda l: l[:, 0], params)
        r = jax.lax.axis_index(PIPE_AXIS)
        is_first = r == 0
        is_last = r == S - 1

        buf0 = _pvary(jnp.zeros_like(xs[0]), (PIPE_AXIS,))
        # accumulate only the m_total final-chunk outputs (not every tick's
        # activation — a v-fold peak-memory saving over stacking scan ys)
        ys0 = _pvary(jnp.zeros_like(xs), (PIPE_AXIS,))

        def tick(carry, t):
            recv, ys = carry
            u = jnp.clip(t - r, 0, n_units - 1)
            c = (u // S) % v
            m = (u // (S * v)) * S + u % S
            # rank 0 injects fresh microbatches for chunk 0; everything
            # else comes off the wire
            mb = jax.lax.dynamic_index_in_dim(xs, m, axis=0, keepdims=False)
            inp = jnp.where(is_first & (c == 0), mb, recv)
            chunk_params = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, c, axis=0, keepdims=False), my_chunks)
            out = fn(chunk_params, inp)
            # final-chunk output of microbatch m: record it.  Clamped
            # warm-up ticks alias (m=0, c=0): harmless, the real write at
            # tick u_f + S - 1 lands later and overwrites.
            prev = jax.lax.dynamic_index_in_dim(ys, m, axis=0,
                                                keepdims=False)
            ys = jax.lax.dynamic_update_index_in_dim(
                ys, jnp.where(c == v - 1, out, prev), m, axis=0)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return (nxt, ys), None

        (_, ys_last), _ = jax.lax.scan(tick, (buf0, ys0),
                                       jnp.arange(total_ticks))
        contrib = jnp.where(is_last, ys_last, jnp.zeros_like(ys_last))
        return jax.lax.psum(contrib, PIPE_AXIS)

    n_dims_x = micro_xs.ndim
    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(
                lambda _: PartitionSpec(None, PIPE_AXIS), stacked_params),
            PartitionSpec(*([None] * n_dims_x)),
        ),
        out_specs=PartitionSpec(*([None] * n_dims_x)),
        axis_names={PIPE_AXIS},
    )
    # reshape stage-major [v*S, ...] -> [v, S, ...] so chunk c of rank r
    # (global stage c*S + r) is leaf[c, r]
    chunked = jax.tree_util.tree_map(
        lambda l: l.reshape((v, S) + l.shape[1:]), stacked_params)
    ys = sm(chunked, micro_xs)
    return ys[:n_micro]
