"""SPMD pipeline-parallel engine.

The TPU-native replacement for the reference's pipeline runtimes — both the
dygraph 1F1B loop (``fleet/meta_parallel/pipeline_parallel.py:387``
forward_backward_pipeline + p2p_communication.py NCCL send/recv) and the
static FleetExecutor actor graph (``fleet_executor/carrier.h:50`` +
interceptors).  Design (scaling-book collective-permute pipelining):

- The pipeline is expressed as ONE differentiable program: a ``lax.scan``
  over schedule ticks inside a ``shard_map`` that is *manual* over the
  "pipe" mesh axis and *auto* (GSPMD) over data/model/sharding/sep axes —
  so TP/DP compose freely inside each stage.
- Micro-batch activations move between stages with ``lax.ppermute``
  (collective-permute rides ICI); XLA overlaps the permute of tick t with
  the compute of tick t+1 — the steady-state overlap the reference builds
  with P2P threads comes from the compiler schedule.
- ``jax.grad`` through the scan+ppermute yields the backward pipeline
  automatically (reversed scan, transposed permutes): a GPipe schedule,
  with per-stage rematerialization via ``jax.checkpoint`` standing in for
  the reference's recompute-in-1F1B memory profile.

Stages must be shape-homogeneous (stage_fn: (stage_params, x) -> y with y
shaped like x) — the transformer-decoder case; embedding/head run outside
the pipelined region (the reference's PipelineLayer shares them across
first/last stages for the same reason, pp_layers.py SharedLayerDesc).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PIPE_AXIS = "pipe"



from .topology import pvary as _pvary


def stack_stage_params(per_stage_params):
    """[pytree per stage] -> single pytree with a leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *per_stage_params)


def shard_stacked_params(stacked, mesh: Mesh):
    """Place stacked stage params with the stage dim over the pipe axis."""
    def place(leaf):
        spec = PartitionSpec(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, stacked)


def pipeline_apply(stage_fn: Callable, stacked_params: Any, micro_xs,
                   n_stages: int, mesh: Mesh,
                   remat: bool = True):
    """Run micro-batches through the stage pipeline.

    stage_fn(stage_params, x) -> y (same shape as x).
    stacked_params: pytree, leaves [n_stages, ...] (sharded over pipe).
    micro_xs: [n_micro, micro_batch, ...] activations entering stage 0.
    Returns ys: [n_micro, micro_batch, ...] — the last stage's outputs,
    valid on every device (broadcast over the pipe axis).
    """
    n_micro = micro_xs.shape[0]
    total_ticks = n_micro + n_stages - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def inner(params, xs):
        # inside shard_map: params leaves have leading dim 1 (this stage)
        my_params = jax.tree_util.tree_map(lambda l: l[0], params)
        stage_idx = jax.lax.axis_index(PIPE_AXIS)
        is_first = stage_idx == 0
        is_last = stage_idx == n_stages - 1

        buf0 = jnp.zeros_like(xs[0])
        # mark the carry as varying over the pipe axis (shard_map VMA typing):
        # the replicated zero init becomes device-varying after the first
        # ppermute, so the scan carry type must start varying.
        buf0 = _pvary(buf0, (PIPE_AXIS,))

        def tick(carry, t):
            recv = carry
            # stage 0 feeds microbatch t (clamped); others take the wire
            mb = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, n_micro - 1), axis=0, keepdims=False)
            inp = jnp.where(is_first, mb, recv)
            out = fn(my_params, inp)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return nxt, out

        _, outs = jax.lax.scan(tick, buf0, jnp.arange(total_ticks))
        # last stage produced valid results at ticks S-1 .. T-1
        ys_last = jax.lax.dynamic_slice_in_dim(outs, n_stages - 1, n_micro,
                                               axis=0)
        # broadcast last stage's outputs to all pipe ranks (psum of masked)
        contrib = jnp.where(is_last, ys_last, jnp.zeros_like(ys_last))
        return jax.lax.psum(contrib, PIPE_AXIS)

    n_dims_x = micro_xs.ndim
    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(lambda _: PartitionSpec(PIPE_AXIS),
                                   stacked_params),
            PartitionSpec(*([None] * n_dims_x)),
        ),
        out_specs=PartitionSpec(*([None] * n_dims_x)),
        axis_names={PIPE_AXIS},
    )
    return sm(stacked_params, micro_xs)


class PipelineStageRunner:
    """Convenience wrapper binding stage_fn + mesh for repeated use."""

    def __init__(self, stage_fn, n_stages, mesh, remat=True):
        self.stage_fn = stage_fn
        self.n_stages = n_stages
        self.mesh = mesh
        self.remat = remat

    def __call__(self, stacked_params, micro_xs):
        return pipeline_apply(self.stage_fn, stacked_params, micro_xs,
                              self.n_stages, self.mesh, self.remat)


def pipeline_apply_interleaved(stage_fn: Callable, stacked_params: Any,
                               micro_xs, n_stages: int, n_chunks: int,
                               mesh: Mesh, remat: bool = True):
    """Interleaved (virtual-stage) pipeline schedule.

    The analogue of the reference's PipelineParallelWithInterleave
    (``fleet/meta_parallel/pipeline_parallel.py:822`` + interleaved
    segmentation in ``pp_layers.py``): each pipe rank holds ``n_chunks``
    virtual stages; global stage ``g = c * n_stages + r`` lives on rank
    ``r`` as chunk ``c``, so a microbatch traverses the ring v times.
    Bubble shrinks from (S-1)/(S-1+M) to (S-1)/(S-1+M*v) schedule units.

    Schedule (Megatron-style unit ordering, n_micro padded to a multiple
    of S): unit ``u`` = microbatch ``m = (u // (S*v))*S + u % S`` at chunk
    ``c = (u // S) % v``; rank r executes unit ``t - r`` at tick t.  The
    unit leaving rank S-1 (chunk c) arrives at rank 0 exactly when chunk
    c+1 of that microbatch is scheduled, so the same wrap-around ppermute
    wire as the GPipe schedule carries all chunk transitions.

    stacked_params: pytree with leaves [n_chunks * n_stages, ...] ordered
    by global stage (stack_stage_params over the g = 0..S*v-1 chain); this
    function reshapes to [v, S, ...] and shards the rank axis over pipe
    itself.  micro_xs: [n_micro, micro, ...].
    """
    S, v = n_stages, n_chunks
    n_micro = micro_xs.shape[0]
    pad = (-n_micro) % S
    if pad:
        micro_xs = jnp.concatenate(
            [micro_xs, jnp.zeros((pad,) + micro_xs.shape[1:],
                                 micro_xs.dtype)], axis=0)
    m_total = n_micro + pad
    n_units = m_total * v
    total_ticks = n_units + S - 1
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    perm = [(i, (i + 1) % S) for i in range(S)]

    def inner(params, xs):
        # params leaves arrive [v, 1, ...] (global [v, S, ...] split on
        # axis 1 = rank); squeeze to [v, ...] = this rank's chunks
        my_chunks = jax.tree_util.tree_map(lambda l: l[:, 0], params)
        r = jax.lax.axis_index(PIPE_AXIS)
        is_first = r == 0
        is_last = r == S - 1

        buf0 = _pvary(jnp.zeros_like(xs[0]), (PIPE_AXIS,))
        # accumulate only the m_total final-chunk outputs (not every tick's
        # activation — a v-fold peak-memory saving over stacking scan ys)
        ys0 = _pvary(jnp.zeros_like(xs), (PIPE_AXIS,))

        def tick(carry, t):
            recv, ys = carry
            u = jnp.clip(t - r, 0, n_units - 1)
            c = (u // S) % v
            m = (u // (S * v)) * S + u % S
            # rank 0 injects fresh microbatches for chunk 0; everything
            # else comes off the wire
            mb = jax.lax.dynamic_index_in_dim(xs, m, axis=0, keepdims=False)
            inp = jnp.where(is_first & (c == 0), mb, recv)
            chunk_params = jax.tree_util.tree_map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, c, axis=0, keepdims=False), my_chunks)
            out = fn(chunk_params, inp)
            # final-chunk output of microbatch m: record it.  Clamped
            # warm-up ticks alias (m=0, c=0): harmless, the real write at
            # tick u_f + S - 1 lands later and overwrites.
            prev = jax.lax.dynamic_index_in_dim(ys, m, axis=0,
                                                keepdims=False)
            ys = jax.lax.dynamic_update_index_in_dim(
                ys, jnp.where(c == v - 1, out, prev), m, axis=0)
            nxt = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return (nxt, ys), None

        (_, ys_last), _ = jax.lax.scan(tick, (buf0, ys0),
                                       jnp.arange(total_ticks))
        contrib = jnp.where(is_last, ys_last, jnp.zeros_like(ys_last))
        return jax.lax.psum(contrib, PIPE_AXIS)

    n_dims_x = micro_xs.ndim
    sm = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(
            jax.tree_util.tree_map(
                lambda _: PartitionSpec(None, PIPE_AXIS), stacked_params),
            PartitionSpec(*([None] * n_dims_x)),
        ),
        out_specs=PartitionSpec(*([None] * n_dims_x)),
        axis_names={PIPE_AXIS},
    )
    # reshape stage-major [v*S, ...] -> [v, S, ...] so chunk c of rank r
    # (global stage c*S + r) is leaf[c, r]
    chunked = jax.tree_util.tree_map(
        lambda l: l.reshape((v, S) + l.shape[1:]), stacked_params)
    ys = sm(chunked, micro_xs)
    return ys[:n_micro]
