"""paddle_tpu.distributed — hybrid-parallel stack over a jax device mesh.

SURVEY §2.5 parity map:
- DP                        -> batch-axis sharding ("data") + GSPMD grad psum
- TP (Column/Row/Vocab)     -> fleet.mp_layers with weight shardings ("model")
- PP (1F1B / interleaved)   -> fleet.pipeline schedules over the "pipe" axis
- sharding (ZeRO 1/2/3)     -> sharded optimizer states / params ("sharding")
- SP / sep (Ulysses)        -> fleet.sequence_parallel ("sep" axis all_to_all)
- EP (MoE)                  -> moe layer with all_to_all dispatch
- HybridCommunicateGroup    -> topology.HybridCommunicateGroup -> jax Mesh
- collective API            -> collective.py (axis-name collectives)
"""

from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,
                  is_initialized)
from .collective import (ReduceOp, all_gather, all_gather_object, all_reduce,
                         alltoall, alltoall_single, barrier, batch_isend_irecv,
                         broadcast, destroy_process_group, get_group, irecv,
                         isend, new_group, P2POp, recv, reduce, reduce_scatter,
                         scatter, send, stream, wait)
from .topology import (AXIS_ORDER, CommunicateTopology,
                       HybridCommunicateGroup, build_mesh, get_global_mesh,
                       set_global_mesh, get_hybrid_communicate_group,
                       set_hybrid_communicate_group)
from .parallel import DataParallel, shard_tensor_dp, spawn
from .sharding_api import shard_tensor, shard_layer, shard_optimizer, reshard
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from .sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
from . import auto_parallel  # noqa: F401
from .utils import recompute  # noqa: F401
from .eager_comm import init_eager_comm, get_eager_comm  # noqa: F401
from . import launch  # noqa: F401
from . import rpc  # noqa: F401
from . import ps  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import checkpoint  # noqa: F401

__all__ = [
    "ParallelEnv", "get_rank", "get_world_size", "init_parallel_env",
    "is_initialized", "ReduceOp", "all_reduce", "all_gather",
    "all_gather_object", "reduce", "reduce_scatter", "alltoall",
    "alltoall_single", "broadcast", "scatter", "send", "recv", "isend",
    "irecv", "barrier", "wait", "stream", "new_group", "get_group",
    "destroy_process_group", "P2POp", "batch_isend_irecv",
    "CommunicateTopology", "HybridCommunicateGroup", "build_mesh",
    "get_global_mesh", "set_global_mesh", "DataParallel", "spawn", "fleet",
    "shard_tensor", "shard_layer", "shard_optimizer", "reshard", "recompute",
    "launch", "sharding", "group_sharded_parallel", "save_group_sharded_model",
]
