"""``paddle_tpu.distributed.rpc`` — minimal tensor-capable RPC (analogue of
``paddle.distributed.rpc`` over ``paddle/fluid/distributed/rpc/rpc_agent.h``;
python surface ``python/paddle/distributed/rpc/__init__.py``: init_rpc,
rpc_sync, rpc_async, shutdown, get_worker_info, get_all_worker_infos).

The reference rides brpc; here each worker runs a small threaded TCP server
executing pickled ``(fn, args, kwargs)`` calls, with rendezvous through the
native TCPStore (runtime/native/tcp_store.cc) — the same store that replaces
the reference's PG bootstrap.  Tensors cross as numpy arrays.
"""

from __future__ import annotations

import concurrent.futures
import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


_state = {"name": None, "rank": None, "workers": {}, "server": None,
          "store_server": None, "pool": None}


def _send_msg(sock, payload: bytes):
    sock.sendall(struct.pack("!Q", len(payload)) + payload)


def _recv_msg(sock) -> bytes:
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        hdr += chunk
    (n,) = struct.unpack("!Q", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed")
        buf += chunk
    return buf


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = pickle.loads(_recv_msg(self.request))
            fn, args, kwargs = req
            try:
                result = ("ok", fn(*args, **kwargs))
            except Exception as e:  # ship the exception back
                result = ("err", e)
            _send_msg(self.request, pickle.dumps(result))
        except ConnectionError:
            pass


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name: str, rank: int = None, world_size: int = None,
             master_endpoint: str = None):
    """Start this worker's RPC server and rendezvous with peers.

    Mirrors the reference contract: every worker calls init_rpc; the master
    endpoint hosts the KV store (worker 0 starts it here).
    """
    from ... import runtime as rt

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT",
        f"127.0.0.1:{os.environ.get('MASTER_PORT', '8813')}")
    host, _, port = master_endpoint.partition(":")
    port = int(port or 8813)

    if rank == 0:
        _state["store_server"] = rt.TCPStoreServer(port)
        port = _state["store_server"].port
    store = None
    deadline = time.time() + 60
    while store is None:
        try:
            store = rt.TCPStore(host, port)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)  # rank 0 has not started the store yet

    server = _Server(("127.0.0.1", 0), _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    my_ip, my_port = server.server_address

    store.set(f"rpc/{rank}", pickle.dumps(
        WorkerInfo(name, rank, "127.0.0.1", my_port)))
    workers = {}
    for r in range(world_size):
        info = pickle.loads(store.get(f"rpc/{r}"))
        workers[info.name] = info
    _state.update(name=name, rank=rank, workers=workers, server=server,
                  pool=concurrent.futures.ThreadPoolExecutor(max_workers=8))
    store.close()


def _call(to: str, fn, args, kwargs, timeout):
    info = _state["workers"].get(to)
    if info is None:
        raise ValueError(f"unknown rpc worker {to!r}; known: "
                         f"{sorted(_state['workers'])}")
    with socket.create_connection((info.ip, info.port),
                                  timeout=timeout or None) as s:
        _send_msg(s, pickle.dumps((fn, args or (), kwargs or {})))
        status, payload = pickle.loads(_recv_msg(s))
    if status == "err":
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=None):
    """Run ``fn(*args)`` on worker ``to``; block for the result."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=None):
    """Run ``fn`` on worker ``to``; returns a Future (``.wait()``/
    ``.result()``)."""
    fut = _state["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # reference API calls it .wait()
    return fut


def get_worker_info(name: str = None) -> WorkerInfo:
    if name is None:
        name = _state["name"]
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["workers"].values())


def shutdown(graceful: bool = True):
    if graceful and _state["rank"] is not None:
        time.sleep(0.05)  # drain in-flight handlers
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=graceful)
    if _state["server"] is not None:
        _state["server"].shutdown()
        _state["server"].server_close()
    if _state["store_server"] is not None:
        _state["store_server"].stop()
    _state.update(name=None, rank=None, workers={}, server=None,
                  store_server=None, pool=None)
