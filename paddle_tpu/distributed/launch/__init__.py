"""``python -m paddle_tpu.distributed.launch`` — distributed job launcher
(analogue of ``python/paddle/distributed/launch/main.py:18`` and its
collective controller ``launch/controllers/collective.py:22``).

TPU-native contract: one process per host drives all local chips (SPMD), so
``--nproc_per_node`` defaults to 1; values >1 exist for the CPU-mesh test
pattern (SURVEY §4: spawn-with-env localhost clusters) and for multi-process
GPU-style debugging.  Env contract matches the reference:

- ``PADDLE_TRAINER_ID``    — global process rank
- ``PADDLE_TRAINERS_NUM``  — world size (nnodes * nproc_per_node)
- ``PADDLE_LOCAL_RANK``    — rank within this host
- ``MASTER_ADDR/PORT``     — coordination service address (jax.distributed
  replaces the reference's TCPStore bootstrap, parallel.py:1088)

Elastic restart (reference fleet/elastic/manager.py:126): ``--max_restart N``
re-launches failed workers from the last checkpoint up to N times.
"""

from .main import launch, main  # noqa: F401

__all__ = ["launch", "main"]
