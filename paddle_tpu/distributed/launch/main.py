"""Launcher implementation.  See package docstring for the env contract."""

from __future__ import annotations

import argparse
import os
import runpy
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _parse(argv):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed training job "
                    "(reference: paddle.distributed.launch)")
    p.add_argument("--master", default=None,
                   help="coordination address ip:port (default: local)")
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_NNODES", 1)))
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", 0)),
                   help="this node's rank in [0, nnodes)")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes on this host (TPU SPMD default: 1)")
    p.add_argument("--devices", default=None,
                   help="device selection string, exported as "
                        "PADDLE_VISIBLE_DEVICES")
    p.add_argument("--job_id", default="default",
                   help="job name, exported as PADDLE_JOB_ID")
    p.add_argument("--log_dir", default="log", help="worker log directory")
    p.add_argument("--max_restart", type=int, default=0,
                   help="elastic: restart failed workers up to N times")
    p.add_argument("--server_num", type=int, default=0,
                   help="PS mode: number of parameter-server processes "
                        "(reference ps controller)")
    p.add_argument("--trainer_num", type=int, default=None,
                   help="PS mode: trainer process count "
                        "(default nproc_per_node)")
    p.add_argument("script", help="training script to run")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank: int) -> dict:
    world = args.nnodes * args.nproc_per_node
    global_rank = args.rank * args.nproc_per_node + local_rank
    env = dict(os.environ)
    env["PADDLE_TRAINER_ID"] = str(global_rank)
    env["PADDLE_TRAINERS_NUM"] = str(world)
    env["PADDLE_LOCAL_RANK"] = str(local_rank)
    env["PADDLE_JOB_ID"] = args.job_id
    if args.master:
        addr, _, port = args.master.partition(":")
        env["MASTER_ADDR"] = addr
        env["MASTER_PORT"] = port or "8787"
    if args.devices is not None:
        env["PADDLE_VISIBLE_DEVICES"] = args.devices
    return env


def _run_in_process(args):
    """Single local worker: exec the script in this interpreter (fast path —
    no fork, keeps the TPU client singleton)."""
    env = _worker_env(args, 0)
    os.environ.update({k: env[k] for k in env
                       if k.startswith(("PADDLE_", "MASTER_"))})
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")
    return 0


def _spawn_workers(args):
    """Reference collective controller: Popen one proc per local rank, tee
    logs, propagate first failure (kill the rest)."""
    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    logs = []
    for lr in range(args.nproc_per_node):
        logf = open(os.path.join(args.log_dir, f"workerlog.{lr}"), "ab")
        cmd = [sys.executable, "-u", args.script] + list(args.script_args)
        procs.append(subprocess.Popen(cmd, env=_worker_env(args, lr),
                                      stdout=logf, stderr=subprocess.STDOUT))
        logs.append(logf)
    rc = 0
    try:
        while procs:
            for i, pr in enumerate(list(procs)):
                r = pr.poll()
                if r is None:
                    continue
                procs.remove(pr)
                if r != 0:
                    rc = r
                    for other in procs:
                        other.send_signal(signal.SIGTERM)
                    for other in procs:
                        other.wait()
                    procs = []
                    break
            time.sleep(0.2)
    finally:
        for f in logs:
            f.close()
    return rc


def _free_port():
    # bind-then-close has a small TOCTOU window before the server rebinds;
    # the server process fails fast (nonzero exit) on a stolen port and
    # kill-on-first-failure below surfaces it instead of hanging
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_ps(args):
    """PS controller (reference launch/controllers/ps.py): spawn server
    procs (TRAINING_ROLE=PSERVER) then trainer procs with the server
    endpoint list in the env contract."""
    os.makedirs(args.log_dir, exist_ok=True)
    if args.nnodes > 1:
        raise SystemExit(
            "PS mode (--server_num) is single-node only for now; "
            "multi-node PS needs externally visible server endpoints")
    n_trainers = (args.trainer_num if args.trainer_num is not None
                  else args.nproc_per_node)
    if n_trainers < 1:
        raise SystemExit("PS mode needs at least one trainer "
                         f"(got --trainer_num {args.trainer_num})")
    endpoints = [f"127.0.0.1:{_free_port()}"
                 for _ in range(args.server_num)]
    procs, logs = [], []

    def start(role, idx, extra_env):
        logf = open(os.path.join(args.log_dir,
                                 f"{role.lower()}log.{idx}"), "ab")
        env = _worker_env(args, idx)
        env["TRAINING_ROLE"] = role
        env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(endpoints)
        env["PADDLE_TRAINERS_NUM"] = str(n_trainers)
        env.update(extra_env)
        cmd = [sys.executable, "-u", args.script] + list(args.script_args)
        procs.append(subprocess.Popen(cmd, env=env, stdout=logf,
                                      stderr=subprocess.STDOUT))
        logs.append(logf)

    for i, ep in enumerate(endpoints):
        start("PSERVER", i, {"PADDLE_CURRENT_ENDPOINT": ep})
    for i in range(n_trainers):
        start("TRAINER", i, {"PADDLE_TRAINER_ID": str(i)})

    # job is done when every TRAINER exits; first failure (trainer OR
    # server) kills the rest — a hung peer must not deadlock the launcher
    trainer_procs = list(procs[args.server_num:])
    server_procs = list(procs[:args.server_num])
    rc = 0
    try:
        live = list(trainer_procs)
        while live:
            for pr in list(live):
                r = pr.poll()
                if r is None:
                    continue
                live.remove(pr)
                if r != 0 and rc == 0:
                    rc = r
                    for other in live:
                        other.send_signal(signal.SIGTERM)
            for pr in server_procs:
                r = pr.poll()
                if r is not None and r != 0 and rc == 0:
                    # a server died mid-job: the trainers can never finish
                    rc = r
                    for other in live:
                        other.send_signal(signal.SIGTERM)
            time.sleep(0.2)
    finally:
        for pr in trainer_procs:
            if pr.poll() is None:
                pr.send_signal(signal.SIGTERM)
        for pr in server_procs:
            pr.send_signal(signal.SIGTERM)
        for pr in procs:
            pr.wait()
        for f in logs:
            f.close()
    return rc


def launch(argv=None):
    args = _parse(sys.argv[1:] if argv is None else argv)
    if args.server_num > 0:
        return _spawn_ps(args)
    attempt = 0
    while True:
        if args.nproc_per_node <= 1 and args.max_restart == 0:
            return _run_in_process(args)
        rc = _spawn_workers(args)
        if rc == 0:
            return 0
        attempt += 1
        if attempt > args.max_restart:
            sys.exit(rc)
        print(f"[launch] workers failed (rc={rc}); elastic restart "
              f"{attempt}/{args.max_restart}", file=sys.stderr)


def main():
    raise SystemExit(launch())
