"""DataParallel wrapper (analogue of paddle.DataParallel,
python/paddle/distributed/parallel.py).

TPU-native DP: there is no EagerReducer bucketing — sharding the batch axis
over the "data" mesh axis makes XLA insert a fused gradient all-reduce over
ICI during the backward of the compiled step (strictly better than bucketed
NCCL calls).  Eagerly (single process) DataParallel is a transparent wrapper
that keeps the reference API (scale_loss, no_sync, state_dict passthrough).
"""

from __future__ import annotations

from contextlib import contextmanager

from ..nn.layer.layers import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    @contextmanager
    def no_sync(self):
        yield

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers")["_layers"], name)


def shard_tensor_dp(x, mesh=None):
    """Shard a batch tensor over the 'data' axis of the global mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from .topology import get_global_mesh
    from ..core.tensor import Tensor
    mesh = mesh or get_global_mesh()
    if mesh is None:
        return x
    spec = PartitionSpec("data", *([None] * (x.ndim - 1)))
    arr = x._value if isinstance(x, Tensor) else x
    out = jax.device_put(arr, NamedSharding(mesh, spec))
    t = Tensor(out, stop_gradient=getattr(x, "stop_gradient", True))
    t._dist_attr = spec
    return t


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Multi-process spawn (reference paddle.distributed.spawn).  On a TPU
    host all local chips belong to one process (SPMD), so nprocs defaults to
    1; multi-host spawn goes through the launch CLI."""
    import multiprocessing as mp
    if nprocs in (-1, 0, 1):
        func(*args)
        return
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        import os
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}

        def target(r=rank, e=env):
            os.environ.update(e)
            func(*args)

        p = ctx.Process(target=target, daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs
