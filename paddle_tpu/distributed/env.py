"""Process-level distributed environment.

Analogue of the reference's launch-env contract (PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / MASTER_ADDR, parallel.py:925 init_parallel_env).  On
JAX, multi-host initialization goes through jax.distributed (the coordination
service replaces TCPStore) and intra-host parallelism is device-level SPMD,
so "rank" here means *process* index for multi-host runs and 0 for the
common single-process multi-device case.
"""

from __future__ import annotations

import os

import jax

_initialized = False


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return int(os.environ.get("PADDLE_LOCAL_RANK",
                                  os.environ.get("LOCAL_RANK", 0)))

    @property
    def nranks(self):
        return get_world_size()

    @property
    def dev_id(self):
        return self.local_rank


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def is_initialized() -> bool:
    return _initialized


def init_parallel_env():
    """Initialize multi-process coordination (reference parallel.py:925).

    Uses env vars compatible with both the reference's launcher contract and
    JAX's: MASTER_ADDR/MASTER_PORT (or PADDLE_MASTER), PADDLE_TRAINER_ID,
    PADDLE_TRAINERS_NUM.  Single-process runs are a no-op — SPMD over local
    devices needs no process group.
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    n_procs = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                 os.environ.get("WORLD_SIZE", 1)))
    if n_procs > 1:
        # must not touch jax.process_count()/devices() here: any backend
        # query initializes XLA and makes jax.distributed.initialize
        # impossible — is_initialized() checks the coordination client
        # without touching backends
        if not jax.distributed.is_initialized():
            addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
            port = os.environ.get("MASTER_PORT", "8787")
            pid = int(os.environ.get("PADDLE_TRAINER_ID",
                                     os.environ.get("RANK", 0)))
            jax.distributed.initialize(
                coordinator_address=f"{addr}:{port}",
                num_processes=n_procs, process_id=pid)
    _initialized = True
    return ParallelEnv()
