"""Elastic training manager (analogue of
``python/paddle/distributed/fleet/elastic/manager.py:126`` ``ElasticManager``
with ``ElasticStatus:48`` / ``LauncherInterface:56``).

The reference watches an ETCD registry of live pods and restarts the whole
job from checkpoint when membership changes.  TPU-native: there is no ETCD;
slice health comes from the JAX coordination service, and elasticity is
checkpoint-restart — the launcher (``launch --max_restart``) re-runs workers,
and this manager supervises a single host's worker processes: watch, kill on
scale events, report status.  (SURVEY §5 failure-detection row: "pod failure
→ whole-job restart from checkpoint; no in-flight recovery" — same model.)
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["ElasticStatus", "LauncherInterface", "ElasticManager",
           "MembershipRegistry", "enable_elastic", "launch_elastic"]


class MembershipRegistry:
    """Live-node registry over the native TCPStore — the ETCD-registry
    analogue the reference manager watches
    (``fleet/elastic/manager.py:126`` watches an etcd prefix of pods).

    Each node slot heartbeats an atomic counter
    (``{prefix}/hb/{slot}``); a node is ALIVE when its counter advanced
    since the previous poll.  ``poll()`` returns the member set and a
    scale event ("scale_up"/"scale_down") when membership changed —
    counters avoid needing key listing or TTLs on the store.
    """

    def __init__(self, store, node_id: int, max_nodes: int = 64,
                 prefix: str = "elastic", heartbeat_interval: float = 0.5):
        self.store = store
        self.node_id = int(node_id)
        self.max_nodes = max_nodes
        self.prefix = prefix
        self.heartbeat_interval = heartbeat_interval
        self._beating = False
        self._thread = None
        self._last_counts = {}

    def _key(self, slot):
        return f"{self.prefix}/hb/{slot}"

    # -- node side ------------------------------------------------------
    def register(self):
        """Start heartbeating this node's slot."""
        import threading
        if self._beating:
            return
        self._beating = True
        self.store.add(self._key(self.node_id), 1)

        def beat():
            while self._beating:
                try:
                    self.store.add(self._key(self.node_id), 1)
                except Exception:
                    pass
                time.sleep(self.heartbeat_interval)

        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()

    def deregister(self):
        self._beating = False
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- manager side ---------------------------------------------------
    def _counts(self):
        out = {}
        for slot in range(self.max_nodes):
            try:
                out[slot] = self.store.add(self._key(slot), 0)
            except Exception:
                out[slot] = 0
        return out

    def snapshot(self):
        """Prime the alive-detection baseline."""
        self._last_counts = self._counts()

    def members(self):
        """Nodes whose heartbeat advanced since the last poll (call at a
        period longer than the heartbeat interval)."""
        now = self._counts()
        alive = sorted(s for s, c in now.items()
                       if c > self._last_counts.get(s, 0))
        self._last_counts = now
        return alive

    def poll(self, prev_members):
        """(members, event): event is "scale_up"/"scale_down"/None."""
        cur = self.members()
        prev = sorted(prev_members)
        if cur == prev:
            return cur, None
        return cur, ("scale_up" if len(cur) > len(prev) else "scale_down")


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class LauncherInterface:
    """Owns local worker processes (reference LauncherInterface:56)."""

    def __init__(self, args):
        self.args = args
        self.procs = []

    def _terminate_procs(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
        self.procs = []

    def launch(self, env=None):
        cmd = list(self.args)
        self.procs.append(subprocess.Popen(cmd, env=env))

    def watch(self):
        """Poll worker status: None while running, else an ElasticStatus."""
        if not self.procs:
            return ElasticStatus.COMPLETED
        codes = [p.poll() for p in self.procs]
        if any(c not in (None, 0) for c in codes):
            return ElasticStatus.ERROR
        if all(c == 0 for c in codes):
            return ElasticStatus.COMPLETED
        return None

    def stop(self):
        self._terminate_procs()


class ElasticManager:
    """Supervise a training command; restart on worker failure (up to
    ``max_restart``) AND on membership scale events when a
    :class:`MembershipRegistry` is attached — the reference's pod-watch
    restart loop, with the new world size exported to the relaunched job
    via ``PADDLE_TRAINERS_NUM``."""

    def __init__(self, cmd, max_restart: int = 3, poll_interval: float = 0.5,
                 registry: "MembershipRegistry" = None):
        self.cmd = list(cmd)
        self.max_restart = max_restart
        self.poll_interval = poll_interval
        self.restarts = 0
        self.launcher = None
        self.registry = registry
        self.events = []           # (event, members) history
        self._members = []

    def _watch_membership(self):
        if self.registry is None:
            return None
        # rate-limit: members() needs polls spaced well past the heartbeat
        # interval (a same-speed poll can miss a live node's beat and
        # thrash restart/scale events forever)
        min_gap = max(self.poll_interval,
                      3.0 * self.registry.heartbeat_interval)
        now = time.time()
        if now - getattr(self, "_last_member_poll", 0.0) < min_gap:
            return None
        self._last_member_poll = now
        cur, event = self.registry.poll(self._members)
        self._members = cur
        if event is not None:
            self.events.append((event, list(cur)))
            return ElasticStatus.RESTART
        return None

    def run(self) -> str:
        if self.registry is not None:
            self.registry.snapshot()
            time.sleep(self.registry.heartbeat_interval * 2)
            self._members = self.registry.members()
        while True:
            env = dict(os.environ)
            if self.registry is not None and self._members:
                env["PADDLE_TRAINERS_NUM"] = str(len(self._members))
            self.launcher = LauncherInterface(self.cmd)
            self.launcher.launch(env=env)
            status = None
            while status is None:
                time.sleep(self.poll_interval)
                status = self.launcher.watch()
                if status is None:
                    status = self._watch_membership()
            if status == ElasticStatus.COMPLETED:
                return ElasticStatus.COMPLETED
            self.launcher.stop()
            if status == ElasticStatus.RESTART:
                print(f"[elastic] membership changed -> "
                      f"{len(self._members)} node(s); restarting",
                      file=sys.stderr)
                continue  # scale events do not consume restart budget
            self.restarts += 1
            if self.restarts > self.max_restart:
                return ElasticStatus.ERROR
            print(f"[elastic] restart {self.restarts}/{self.max_restart}",
                  file=sys.stderr)

    def exit(self):
        if self.launcher:
            self.launcher.stop()


def enable_elastic(args=None, etcd=None) -> bool:
    """Reference ``enable_elastic``: True when an elastic registry is
    configured.  Here: when PADDLE_ELASTIC_MAX_RESTART requests it."""
    return int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", 0)) > 0


def launch_elastic(cmd=None, max_restart=None) -> str:
    """Entry (reference fleet/elastic/__init__.py:49): supervise ``cmd``
    (defaults to re-running sys.argv as a worker)."""
    cmd = cmd or [sys.executable] + sys.argv
    if max_restart is None:
        max_restart = int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", 3))
    return ElasticManager(cmd, max_restart=max_restart).run()
