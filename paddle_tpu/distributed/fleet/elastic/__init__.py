"""Elastic training manager (analogue of
``python/paddle/distributed/fleet/elastic/manager.py:126`` ``ElasticManager``
with ``ElasticStatus:48`` / ``LauncherInterface:56``).

The reference watches an ETCD registry of live pods and restarts the whole
job from checkpoint when membership changes.  TPU-native: there is no ETCD;
slice health comes from the JAX coordination service, and elasticity is
checkpoint-restart — the launcher (``launch --max_restart``) re-runs workers,
and this manager supervises a single host's worker processes: watch, kill on
scale events, report status.  (SURVEY §5 failure-detection row: "pod failure
→ whole-job restart from checkpoint; no in-flight recovery" — same model.)
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["ElasticStatus", "LauncherInterface", "ElasticManager",
           "enable_elastic", "launch_elastic"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class LauncherInterface:
    """Owns local worker processes (reference LauncherInterface:56)."""

    def __init__(self, args):
        self.args = args
        self.procs = []

    def _terminate_procs(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            while p.poll() is None and time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
        self.procs = []

    def launch(self):
        cmd = list(self.args)
        self.procs.append(subprocess.Popen(cmd))

    def watch(self):
        """Poll worker status: None while running, else an ElasticStatus."""
        if not self.procs:
            return ElasticStatus.COMPLETED
        codes = [p.poll() for p in self.procs]
        if any(c not in (None, 0) for c in codes):
            return ElasticStatus.ERROR
        if all(c == 0 for c in codes):
            return ElasticStatus.COMPLETED
        return None

    def stop(self):
        self._terminate_procs()


class ElasticManager:
    """Supervise a training command; on worker failure restart it (up to
    ``max_restart``), mirroring the reference's pod-level restart loop."""

    def __init__(self, cmd, max_restart: int = 3, poll_interval: float = 0.5):
        self.cmd = list(cmd)
        self.max_restart = max_restart
        self.poll_interval = poll_interval
        self.restarts = 0
        self.launcher = None

    def run(self) -> str:
        while True:
            self.launcher = LauncherInterface(self.cmd)
            self.launcher.launch()
            status = None
            while status is None:
                time.sleep(self.poll_interval)
                status = self.launcher.watch()
            if status == ElasticStatus.COMPLETED:
                return ElasticStatus.COMPLETED
            self.launcher.stop()
            self.restarts += 1
            if self.restarts > self.max_restart:
                return ElasticStatus.ERROR
            print(f"[elastic] restart {self.restarts}/{self.max_restart}",
                  file=sys.stderr)

    def exit(self):
        if self.launcher:
            self.launcher.stop()


def enable_elastic(args=None, etcd=None) -> bool:
    """Reference ``enable_elastic``: True when an elastic registry is
    configured.  Here: when PADDLE_ELASTIC_MAX_RESTART requests it."""
    return int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", 0)) > 0


def launch_elastic(cmd=None, max_restart=None) -> str:
    """Entry (reference fleet/elastic/__init__.py:49): supervise ``cmd``
    (defaults to re-running sys.argv as a worker)."""
    cmd = cmd or [sys.executable] + sys.argv
    if max_restart is None:
        max_restart = int(os.environ.get("PADDLE_ELASTIC_MAX_RESTART", 3))
    return ElasticManager(cmd, max_restart=max_restart).run()
