"""PipelineParallel trainer (analogue of
fleet/meta_parallel/pipeline_parallel.py: PipelineParallel:132,
forward_backward_pipeline:387, train_batch:590).

Scheduling semantics on TPU: micro-batch gradient accumulation is executed
directly (the schedule below mirrors 1F1B's per-microbatch fw/bw ordering);
on a multi-device mesh the compiled train step (pipeline_engine) overlaps
stages via collective-permute — XLA owns the steady-state overlap that the
reference achieves with P2P send/recv threads (p2p_communication.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ....core.tensor import Tensor
from .parallel_layers.pp_layers import PipelineLayer
from .meta_parallel_base import MetaParallelBase


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self.accumulate_steps = int(
            strategy.pipeline_configs.get("accumulate_steps", 1))
        self.micro_batch_size = int(
            strategy.pipeline_configs.get("micro_batch_size", 1))
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            xs, ys = data
        else:
            xs, ys = data, None
        m = self.accumulate_steps
        micro = []
        for i in range(m):
            lo = i * self.micro_batch_size
            hi = lo + self.micro_batch_size
            x_i = xs[lo:hi]
            y_i = ys[lo:hi] if ys is not None else None
            micro.append((x_i, y_i))
        return micro

    def forward_backward_pipeline(self, data, scaler=None):
        """Micro-batch fw/bw with 1F1B ordering (single-program execution)."""
        layers = self._layers
        loss_fn = layers._loss_fn
        total = None
        for x_i, y_i in self._split_micro(data):
            out = layers(x_i)
            loss = loss_fn(out, y_i) if loss_fn is not None else out
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss.detach() if total is None else total + loss.detach()
        self.total_loss = total / self.accumulate_steps
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ....core.tape import no_grad
        layers = self._layers
        loss_fn = layers._loss_fn
        total = None
        with no_grad():
            for x_i, y_i in self._split_micro(data):
                out = layers(x_i)
                if compute_loss and loss_fn is not None:
                    out = loss_fn(out, y_i)
                total = out if total is None else total + out
        return total / self.accumulate_steps if compute_loss else total


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual-stage) schedule (reference :822).  Virtual stages
    change device placement, not math — accepted and run with the same
    accumulation semantics here."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
