"""ShardingParallel wrapper (analogue of
fleet/meta_parallel/sharding_parallel.py)."""

from __future__ import annotations

from .meta_parallel_base import MetaParallelBase


class ShardingParallel(MetaParallelBase):
    pass
