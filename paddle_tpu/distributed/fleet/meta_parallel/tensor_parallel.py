"""TensorParallel wrapper (analogue of fleet/meta_parallel/tensor_parallel.py).

On GSPMD there is no input-broadcast step (inputs are logically global), so
the wrapper's job is just API parity + ensuring mp-layer annotations exist.
"""

from __future__ import annotations

from .meta_parallel_base import MetaParallelBase


class TensorParallel(MetaParallelBase):
    pass
