"""Meta-parallel wrappers (analogue of fleet/meta_parallel/)."""

from .parallel_layers.mp_layers import (ColumnParallelLinear,
                                        RowParallelLinear,
                                        VocabParallelEmbedding,
                                        ParallelCrossEntropy)
from .parallel_layers.pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer
from .parallel_layers.random import (RNGStatesTracker, get_rng_state_tracker,
                                     model_parallel_random_seed)
from .tensor_parallel import TensorParallel
from .pipeline_parallel import PipelineParallel
from .sharding_parallel import ShardingParallel

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy", "LayerDesc",
           "SharedLayerDesc", "PipelineLayer", "RNGStatesTracker",
           "get_rng_state_tracker", "model_parallel_random_seed",
           "TensorParallel", "PipelineParallel", "ShardingParallel"]
