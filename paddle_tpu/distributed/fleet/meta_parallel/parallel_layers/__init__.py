from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                        VocabParallelEmbedding, ParallelCrossEntropy)
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer
from .random import (RNGStatesTracker, get_rng_state_tracker,
                     model_parallel_random_seed)
