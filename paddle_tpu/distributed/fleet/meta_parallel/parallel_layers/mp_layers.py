"""Megatron tensor-parallel layers.

Analogue of ``python/paddle/distributed/fleet/layers/mpu/mp_layers.py``
(VocabParallelEmbedding:44, ColumnParallelLinear:312, RowParallelLinear:524,
ParallelCrossEntropy:729).

TPU-native design (GSPMD): each layer holds the FULL logical weight with a
sharding annotation over the "model" mesh axis.  Under jit on a mesh, GSPMD
splits the math and inserts the same collectives the reference codes by hand
(identity/allreduce pairs, vocab-parallel masked lookup + allreduce).  The
``gather_output`` / ``input_is_parallel`` flags become output/input sharding
constraints.  Eagerly on one device the layers behave like their serial
counterparts — matching the reference's world_size==1 fast path (mp_layers.py
falls back to F.linear when mp==1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from .....core.tensor import Tensor
from ..... import nn
from .....nn import functional as F
from .....nn.layer.layers import Layer
from ....topology import get_global_mesh

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy"]

MODEL_AXIS = "model"

# Leading (batch/seq) dims of activation constraints stay UNCONSTRAINED so
# GSPMD preserves whatever dp/sharding layout the caller established; pinning
# them to None (replicated) forces an involuntary full rematerialization
# (batch-sharded -> replicated reshard) on every constrained activation.
_U = PartitionSpec.UNCONSTRAINED


def _annotate(param, spec):
    param._dist_attr = spec
    mesh = get_global_mesh()
    if mesh is not None and MODEL_AXIS in mesh.axis_names and \
            not isinstance(param._value, jax.core.Tracer):
        try:
            param._value = jax.device_put(param._value,
                                          NamedSharding(mesh, spec))
        except Exception:
            pass  # single-device mesh or placement unavailable eagerly
    return param


def _constrain(x, spec):
    """Apply a sharding constraint under jit; no-op eagerly."""
    mesh = get_global_mesh()
    if mesh is None:
        return x
    from .....core.dispatch import dispatch

    def impl(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
        return a

    return dispatch("sharding_constraint", impl, (x,))


class VocabParallelEmbedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        from .....nn.initializer import XavierNormal
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierNormal())
        # vocab dim sharded over model axis (reference shards rows per rank)
        _annotate(self.weight, PartitionSpec(MODEL_AXIS, None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        from .....nn.initializer import XavierNormal
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        _annotate(self.weight, PartitionSpec(None, MODEL_AXIS))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            _annotate(self.bias, PartitionSpec(MODEL_AXIS))
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep activations sharded along the model axis (last dim)
            ndim = out.ndim
            out = _constrain(out, PartitionSpec(*([_U] * (ndim - 1)),
                                                MODEL_AXIS))
        return out


class RowParallelLinear(Layer):
    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        from .....nn.initializer import XavierNormal
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        _annotate(self.weight, PartitionSpec(MODEL_AXIS, None))
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            ndim = x.ndim
            x = _constrain(x, PartitionSpec(*([_U] * (ndim - 1)), MODEL_AXIS))
        # contraction dim sharded -> GSPMD inserts the allreduce the
        # reference does via mp_allreduce (mp_ops.py:285)
        out = F.linear(x, self.weight, self.bias)
        ndim = out.ndim
        # last dim un-sharded (the allreduce point); batch dims stay free
        return _constrain(out, PartitionSpec(*([_U] * (ndim - 1)), None))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax cross-entropy (reference mp_layers.py:729 /
    _c_softmax_with_cross_entropy).  With logits sharded over the vocab dim,
    the fused log-softmax + gather below lets GSPMD keep the reduction local
    and emit one allreduce of scalars — same comm volume as the reference's
    custom kernel."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        from .....core.dispatch import dispatch
        ignore_index = self.ignore_index

        def impl(logits, lbl):
            lse = jax.scipy.special.logsumexp(
                logits.astype(jnp.float32), axis=-1)
            idx = lbl.astype(jnp.int32)
            squeeze = idx.ndim == logits.ndim
            if squeeze:
                idx = idx[..., 0]
            picked = jnp.take_along_axis(
                logits.astype(jnp.float32), idx[..., None], axis=-1)[..., 0]
            loss = lse - picked
            if ignore_index >= 0:
                loss = jnp.where(idx == ignore_index, 0.0, loss)
            return loss[..., None] if squeeze else loss

        return dispatch("parallel_cross_entropy", impl, (input, label),
                        nondiff_mask=[False, True])
