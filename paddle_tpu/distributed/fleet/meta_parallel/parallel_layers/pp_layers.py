"""Pipeline layer description & segmentation.

Analogue of ``fleet/meta_parallel/parallel_layers/pp_layers.py`` (LayerDesc:56,
SharedLayerDesc:76, SegmentLayers:92, PipelineLayer:239).  PipelineLayer keeps
the reference's description API; stage assignment feeds the shard_map pipeline
engine (paddle_tpu.distributed.pipeline_engine) on a mesh, and runs serially
(functionally identical) on one device.
"""

from __future__ import annotations

import math
from typing import List, Optional

from .....nn.layer.layers import Layer
from .....nn.layer.container import LayerList

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr
                 ="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layer descs into num_parts stages (reference :92)."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 num_virtual_pipeline_stage=None):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        assert self.num_items >= self.num_parts

    def do_segment(self):
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            # segment by layer class name occurrences
            name = self.method.split(":", 1)[1]
            marks = [0]
            cnt = sum(1 for d in self._layers_desc
                      if self._name_of(d) == name)
            per = cnt // self.num_parts
            assert per > 0, "fewer marked layers than stages"
            seen = 0
            for i, d in enumerate(self._layers_desc):
                if self._name_of(d) == name:
                    seen += 1
                    if seen % per == 0 and len(marks) < self.num_parts:
                        marks.append(i + 1)
            marks.append(self.num_items)
            while len(marks) < self.num_parts + 1:
                marks.append(self.num_items)
            return marks
        if self.method == "parameters":
            weights = [self._param_count(d) or 1 for d in self._layers_desc]
            total = sum(weights)
            target = total / self.num_parts
            marks = [0]
            acc = 0
            for i, w in enumerate(weights):
                acc += w
                if acc >= target and len(marks) < self.num_parts:
                    marks.append(i + 1)
                    acc = 0
            marks.append(self.num_items)
            while len(marks) < self.num_parts + 1:
                marks.append(self.num_items)
            return marks
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def _name_of(desc):
        if isinstance(desc, LayerDesc):
            return desc.layer_func.__name__
        return type(desc).__name__

    @staticmethod
    def _param_count(desc):
        return 0  # uniform fallback weight for non-built descs

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """Reference PipelineLayer:239.  Holds the full layer list; ``forward``
    runs end-to-end (single-program SPMD semantics).  ``get_stage_layers``
    exposes per-stage slices for the pipeline engine; shared embeddings
    (SharedLayerDesc with the same key) share one parameter instance."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0,
                 num_virtual_pipeline_stages=None, **kwargs):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._recompute_interval = recompute_interval
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or 1
        self._shared = {}

        seg = SegmentLayers(self._layers_desc, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()

        built = []
        for desc in self._layers_desc:
            if isinstance(desc, SharedLayerDesc):
                if desc.layer_name not in self._shared:
                    self._shared[desc.layer_name] = desc.build_layer()
                layer = self._shared[desc.layer_name]
                built.append((layer, desc.forward_func))
            elif isinstance(desc, LayerDesc):
                built.append((desc.build_layer(), None))
            elif isinstance(desc, Layer):
                built.append((desc, None))
            elif callable(desc):
                built.append((desc, None))
            else:
                raise TypeError(f"bad layer desc {desc!r}")
        self.run_function = built
        self._layer_list = LayerList(
            [l for l, _ in built if isinstance(l, Layer)])

    @property
    def num_stages(self):
        return self._num_stages

    def get_num_virtual_stages(self):
        return 1

    def stage_boundaries(self, stage_id):
        return self.segment_parts[stage_id], self.segment_parts[stage_id + 1]

    def get_stage_layers(self, stage_id):
        s, e = self.stage_boundaries(stage_id)
        return self.run_function[s:e]

    def forward(self, input, chunk_id=None):
        x = input
        for i, (layer, fwd) in enumerate(self.run_function):
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(layer, Layer):
                if self._recompute_interval > 0 and \
                        i % self._recompute_interval == 0 and self.training:
                    from ....utils import recompute
                    x = recompute(layer, x)
                else:
                    x = layer(x)
            else:
                x = layer(x)
        return x
