"""RNG state tracking for tensor parallel (analogue of
fleet/layers/mpu/random.py: RNGStatesTracker:34,
model_parallel_random_seed:88).

On the counter-based PRNG, a "state" is just (base_key, offset); tracker
contexts swap the active stream so dropout inside TP blocks draws from the
local-per-rank stream while everything else draws from the global one.
"""

from __future__ import annotations

from contextlib import contextmanager

from .....core.generator import Generator, default_generator

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            if n not in self.states_:
                self.states_[n] = Generator(0)
            self.states_[n].set_state(s)

    @contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        import paddle_tpu.core.generator as genmod
        global_gen = genmod._default_generator
        genmod._default_generator = self.states_[name]
        try:
            yield
        finally:
            genmod._default_generator = global_gen


_rng_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _rng_tracker


def model_parallel_random_seed(seed=None):
    import random
    from ....topology import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    rank = hcg.get_model_parallel_rank() if hcg else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + rank * 100
    else:
        global_seed = random.randint(0, 100000)
        local_seed = global_seed + 1024 + rank * 100
    _rng_tracker.reset()
    _rng_tracker.add(MODEL_PARALLEL_RNG, local_seed)
    default_generator().manual_seed(global_seed)
