"""Sequence-parallel utilities (analogue of
fleet/utils/sequence_parallel_utils.py: ScatterOp:83, GatherOp:95,
AllGatherOp:109, mark_as_sequence_parallel_parameter:146).

TPU-native: scatter/gather of activations along the sequence dim are
sharding-constraint changes — GSPMD emits the all-gather / reduce-scatter
pair the reference implements as autograd ops.  The "sep"/"model" axis names
match the topology mesh.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ....core.dispatch import dispatch
from ....core.tensor import Tensor
from ...topology import get_global_mesh

__all__ = ["ScatterOp", "GatherOp", "AllGatherOp", "ReduceScatterOp",
           "scatter", "all_gather",
           "mark_as_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]

SEQ_AXIS_NAME = "model"  # Megatron-SP shards seq dim over the TP axis


def _constrain_seq(x, shard: bool, seq_dim=0):
    mesh = get_global_mesh()
    if mesh is None:
        return x if isinstance(x, Tensor) else Tensor(jax.numpy.asarray(x))
    axes = [None] * x.ndim
    if shard:
        axes[seq_dim] = SEQ_AXIS_NAME
    spec = PartitionSpec(*axes)

    def impl(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, NamedSharding(mesh, spec))
        return a

    return dispatch("seq_parallel_constraint", impl, (x,))


def scatter(x, seq_dim=0):
    """Split activations along seq dim across the TP axis (ScatterOp)."""
    return _constrain_seq(x, shard=True, seq_dim=seq_dim)


def all_gather(x, seq_dim=0):
    """Gather sequence shards (AllGatherOp/GatherOp)."""
    return _constrain_seq(x, shard=False, seq_dim=seq_dim)


class ScatterOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return scatter(x, seq_dim)


class GatherOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return all_gather(x, seq_dim)


class AllGatherOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return all_gather(x, seq_dim)


class ReduceScatterOp:
    @staticmethod
    def apply(x, seq_dim=0):
        return scatter(x, seq_dim)


def mark_as_sequence_parallel_parameter(parameter):
    parameter.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """Under GSPMD the grads of sequence-parallel params are reduced by the
    compiler; the hook registration is accepted for API parity."""
    return None
