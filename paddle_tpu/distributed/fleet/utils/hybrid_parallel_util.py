"""Hybrid-parallel gradient helpers (analogue of
fleet/utils/hybrid_parallel_util.py: fused_allreduce_gradients:241,
broadcast_mp_parameters:213).

Under compiled SPMD these reductions are emitted by GSPMD; the functions are
correct no-ops/identities in single-program execution and exist for recipe
compatibility.
"""

from __future__ import annotations

__all__ = ["fused_allreduce_gradients", "broadcast_mp_parameters",
           "broadcast_dp_parameters", "broadcast_sharding_parameters"]


def fused_allreduce_gradients(parameter_list, hcg):
    return None


def broadcast_mp_parameters(model, hcg):
    return None


def broadcast_dp_parameters(model, hcg):
    return None


def broadcast_sharding_parameters(model, hcg):
    return None
