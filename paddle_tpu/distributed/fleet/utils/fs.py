"""Filesystem abstraction for checkpoints/data.

Reference parity: ``python/paddle/distributed/fleet/utils/fs.py``
(LocalFS:113, HDFSClient:424, AFSClient:1152).  LocalFS is fully
functional; HDFSClient shells out to a configured ``hadoop`` binary and
raises clearly when one is not present (this build is air-gapped).
"""

from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class ExecuteError(Exception):
    """A filesystem shell command exited nonzero (reference fs.py
    ExecuteError)."""


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def upload_dir(self, local_dir, dest_dir):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference LocalFS — same method surface)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists:
            if not self.is_exist(src_path):
                raise FSFileNotExistsError(f"{src_path} not found")
            if self.is_exist(dst_path) and not overwrite:
                raise FSFileExistsError(f"{dst_path} already exists")
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(f"{fs_path} already exists")
            return
        with open(fs_path, "a"):
            pass

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def upload_dir(self, local_dir, dest_dir):
        shutil.copytree(local_dir, dest_dir)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient(FS):
    """HDFS via the hadoop CLI (reference HDFSClient shells out the same
    way).  Requires a working ``hadoop`` executable."""

    def __init__(self, hadoop_home=None, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        if shutil.which(self._hadoop) is None:
            raise RuntimeError(
                f"hadoop executable {self._hadoop!r} not found; HDFSClient "
                "needs a Hadoop installation (air-gapped CI uses LocalFS)")

    def _run(self, *args, check=False):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise ExecuteError(
                f"{' '.join(cmd)} failed (exit {proc.returncode}): "
                f"{proc.stderr.strip()}")
        return proc.returncode, proc.stdout

    def is_exist(self, fs_path):
        code, _ = self._run("-test", "-e", fs_path)
        return code == 0

    def is_dir(self, fs_path):
        code, _ = self._run("-test", "-d", fs_path)
        return code == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        _, out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path, check=True)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path, check=True)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path, check=True)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path, check=True)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path, check=True)

    def need_upload_download(self):
        return True

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(f"{fs_path} already exists")
        self._run("-touchz", fs_path, check=True)
