from . import sequence_parallel_utils  # noqa: F401
from . import fs  # noqa: F401
from .fs import LocalFS, HDFSClient  # noqa: F401
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: F401
from ..recompute import recompute  # noqa: F401  (reference fleet.utils.recompute)
