"""Activation recompute under hybrid parallelism (analogue of
``python/paddle/distributed/fleet/recompute/`` — recompute.py:384,
recompute_hybrid.py).

TPU-native: rematerialization is ``jax.checkpoint`` under the tape (see
``paddle_tpu.distributed.utils.recompute``).  The reference's hybrid variant
exists to coordinate per-rank RNG and optionally offload checkpointed
activations to host memory; on the SPMD path RNG is already coherent (trace
keys are split deterministically per microbatch/segment), and offload is a
checkpoint *policy* rather than a manual D2H copy.
"""

from __future__ import annotations

from ...utils import recompute, recompute_sequential

__all__ = ["recompute", "recompute_sequential", "recompute_hybrid"]


def recompute_hybrid(ctx, function, *args, **kwargs):
    """Recompute a segment under hybrid parallelism (reference
    ``recompute_hybrid.py``: ``_HPRecomputeFunction``).

    ``ctx`` mirrors the reference dict: ``mp_group`` (ignored — SPMD keeps
    TP ranks in lockstep by construction), ``offload`` (save residuals to
    host memory via an offload checkpoint policy where supported), and
    ``partition`` (the reference splits saved activations across the mp
    group; GSPMD keeps activations sharded by their producing op, so this
    is already the default).
    """
    del ctx  # mp_group/offload/partition: handled by SPMD + XLA (see above)
    return recompute(function, *args, **kwargs)
