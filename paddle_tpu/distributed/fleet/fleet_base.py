"""Fleet singleton + DistributedStrategy (reference fleet.py:99,
distributed_strategy.py:121)."""

from __future__ import annotations

import os
from typing import Optional

from ..topology import (CommunicateTopology, HybridCommunicateGroup,
                        set_hybrid_communicate_group,
                        get_hybrid_communicate_group as _get_hcg)


class _HybridConfig(dict):
    def __getattr__(self, k):
        return self[k]


class DistributedStrategy:
    """Switch container (reference wraps distributed_strategy.proto; here a
    plain object with the same field names used by the training recipes)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.fuse_all_reduce_ops = True
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid_configs={self.hybrid_configs})"


class Fleet:
    def __init__(self):
        self._is_initialized = False
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._user_defined_optimizer = None
        self._role_maker = None
        self._ps_server = None
        self._ps_client = None

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        if role_maker is None and not is_collective:
            # reference default: PS mode constructs a cloud role maker
            # reading the launcher's env contract
            from .role_maker import PaddleCloudRoleMaker
            role_maker = PaddleCloudRoleMaker()
        self._role_maker = role_maker
        if role_maker is not None and not is_collective \
                and not getattr(role_maker, "_is_collective", False):
            # parameter-server mode (reference: the_one_ps workflow —
            # servers call init_server()/run_server(), workers
            # init_worker() then train; tables live on the native PS)
            self._strategy = strategy or DistributedStrategy()
            self._is_initialized = True
            return self
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        dp = int(hc.get("dp_degree", 1))
        mp = int(hc.get("mp_degree", 1))
        pp = int(hc.get("pp_degree", 1))
        sh = int(hc.get("sharding_degree", 1))
        sep = int(hc.get("sep_degree", 1))
        import jax
        n_dev = len(jax.devices())
        # auto-fill dp like the reference launcher: remaining devices -> dp
        specified = mp * pp * sh * sep * dp
        if specified < n_dev and n_dev % (mp * pp * sh * sep) == 0 and dp == 1:
            dp = n_dev // (mp * pp * sh * sep)
            hc["dp_degree"] = dp
        topo = CommunicateTopology(
            ["data", "pipe", "sharding", "sep", "model"],
            [dp, pp, sh, sep, mp])
        self._hcg = HybridCommunicateGroup(topo)
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    def _ps_role_maker(self):
        # only PS-mode role makers own the worker topology; collective
        # runs keep using the process-group rank/world
        rm = self._role_maker
        if rm is not None and not getattr(rm, "_is_collective", False):
            return rm
        return None

    def is_first_worker(self):
        rm = self._ps_role_maker()
        if rm is not None:
            return rm.is_first_worker()
        from ..env import get_rank
        return get_rank() == 0

    def worker_index(self):
        rm = self._ps_role_maker()
        if rm is not None:
            return rm.worker_index()
        from ..env import get_rank
        return get_rank()

    def worker_num(self):
        rm = self._ps_role_maker()
        if rm is not None:
            return rm.worker_num()
        from ..env import get_world_size
        return get_world_size()

    def get_hybrid_communicate_group(self):
        return self._hcg

    def distributed_model(self, model):
        from .model import distributed_model as _dm
        return _dm(model, self._hcg, self._strategy)

    def distributed_optimizer(self, optimizer, strategy=None):
        from .meta_optimizers.hybrid_parallel_optimizer import (
            HybridParallelOptimizer)
        self._user_defined_optimizer = optimizer
        if self._hcg is not None and self._hcg.nranks > 1:
            return HybridParallelOptimizer(optimizer, self._hcg,
                                           self._strategy)
        return optimizer

    def barrier_worker(self):
        pass

    # ---- parameter-server mode (reference fleet PS surface) ----
    def is_server(self):
        return (self._role_maker is not None
                and self._role_maker.is_server())

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def init_server(self, *args, **kwargs):
        """Start the native parameter server on this node's endpoint."""
        from ..ps import PSServer
        if not self.is_server():
            raise RuntimeError("init_server() called on a non-server role")
        ep = self._role_maker._current_endpoint
        port = int(ep.rsplit(":", 1)[1]) if ":" in ep else 0
        self._ps_server = PSServer(port)
        return self._ps_server

    def run_server(self, block=True, poll_interval_s=0.5):
        """Blocks serving requests until ``stop_server()`` (or process
        signal) — reference run_server semantics: the canonical server
        script is ``init_server(); run_server()`` with nothing after.
        Pass ``block=False`` to only assert liveness (in-process tests)."""
        if self._ps_server is None:
            raise RuntimeError("run_server() before init_server()")
        if block:
            import time as _time
            while self._ps_server is not None:
                _time.sleep(poll_interval_s)
        return self._ps_server

    def init_worker(self, *args, **kwargs):
        """Connect to the configured server endpoints (a single
        PSClient, or a ShardedPSClient spanning all of them)."""
        from ..ps import PSClient
        eps = (self._role_maker.get_pserver_endpoints()
               if self._role_maker else [])
        if not eps:
            raise RuntimeError(
                "init_worker(): no PADDLE_PSERVERS_IP_PORT_LIST endpoints")
        if len(eps) > 1:
            from ..ps import ShardedPSClient
            self._ps_client = ShardedPSClient(eps)
            return self._ps_client
        host, port = eps[0].rsplit(":", 1)
        self._ps_client = PSClient(host, int(port))
        return self._ps_client

    def ps_client(self):
        if self._ps_client is None:
            raise RuntimeError("PS client not initialized; call "
                               "fleet.init_worker() first")
        return self._ps_client

    def stop_worker(self):
        if self._ps_client is not None:
            self._ps_client.close()
            self._ps_client = None

    def stop_server(self):
        if self._ps_server is not None:
            self._ps_server.stop()
            self._ps_server = None


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
    return fleet.init(role_maker, is_collective, strategy, log_level)


def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return fleet._hcg or _get_hcg()
