"""paddle_tpu.distributed.fleet — the Fleet facade (analogue of
python/paddle/distributed/fleet/fleet.py:99).
"""

from .fleet_base import (DistributedStrategy, Fleet, fleet, init,
                         distributed_model, distributed_optimizer,
                         get_hybrid_communicate_group)
from . import meta_parallel  # noqa: F401
from .meta_parallel.parallel_layers.mp_layers import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)
from .meta_parallel.parallel_layers.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed)
from .utils import sequence_parallel_utils  # noqa: F401
from . import recompute as recompute_mod  # noqa: F401
from . import elastic  # noqa: F401
from .recompute import recompute, recompute_sequential, recompute_hybrid  # noqa: F401
from .role_maker import (PaddleCloudRoleMaker, UserDefinedRoleMaker,  # noqa: F401
                         Role)

__all__ = ["Fleet", "fleet", "init", "DistributedStrategy",
           "distributed_model", "distributed_optimizer",
           "get_hybrid_communicate_group", "meta_parallel",
           "ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy",
           "recompute", "recompute_sequential", "recompute_hybrid",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "Role"]
