"""Role makers for parameter-server mode.

Reference parity: ``python/paddle/distributed/fleet/base/role_maker.py``
(PaddleCloudRoleMaker reads TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST /
PADDLE_TRAINERS_NUM etc. from the launch environment).
"""

from __future__ import annotations

import os

__all__ = ["Role", "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]


class Role:
    WORKER = 1
    SERVER = 2


class PaddleCloudRoleMaker:
    """Reads the launch CLI's env contract (reference role_maker env keys)."""

    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints = [e for e in eps.split(",") if e]
        self._worker_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._worker_index = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                                "")

    def is_server(self):
        return self._role == Role.SERVER

    def is_worker(self):
        return self._role == Role.WORKER

    def is_first_worker(self):
        return self.is_worker() and self._worker_index == 0

    def worker_num(self):
        return self._worker_num

    def worker_index(self):
        return self._worker_index

    def server_num(self):
        return len(self._server_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Explicit role assignment (reference UserDefinedRoleMaker)."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, **kwargs):
        self._is_collective = False
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_num = worker_num
        self._worker_index = current_id
        # a server's own endpoint is its slot in the server list
        # (reference UserDefinedRoleMaker semantics)
        if role == Role.SERVER and current_id < len(self._server_endpoints):
            self._current_endpoint = self._server_endpoints[current_id]
        else:
            self._current_endpoint = ""
