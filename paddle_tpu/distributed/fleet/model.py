"""fleet.distributed_model (analogue of fleet/model.py:30)."""

from __future__ import annotations

from .meta_parallel import (PipelineParallel, ShardingParallel,
                            TensorParallel)
from .meta_parallel.parallel_layers.pp_layers import PipelineLayer
from ..parallel import DataParallel


def distributed_model(model, hcg=None, strategy=None):
    if hcg is None:
        from .fleet_base import fleet as _fleet
        hcg = _fleet._hcg
        strategy = strategy or _fleet._strategy
    if hcg is None:
        return model
    mode = hcg.get_parallel_mode()
    if mode == "pipeline" or isinstance(model, PipelineLayer):
        return PipelineParallel(model, hcg, strategy)
    if mode == "model_parallel":
        return TensorParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return ShardingParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model)
    return model
