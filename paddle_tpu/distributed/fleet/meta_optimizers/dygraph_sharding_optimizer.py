"""DygraphShardingOptimizer — ZeRO stage-1 (analogue of
meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:39).

TPU-native: instead of rank-partitioned python param lists + broadcast, the
optimizer annotates its accumulators with a sharding over the "sharding"
mesh axis.  Under the compiled train step, GSPMD keeps optimizer states
sharded (ZeRO-1 memory) and the param update gathers via ICI — the same
memory/communication tradeoff as the reference's shard+broadcast, chosen by
the compiler.  Eagerly (1 device) it is a passthrough.
"""

from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ...topology import get_global_mesh

SHARDING_AXIS = "sharding"


class DygraphShardingOptimizer:
    def __init__(self, optimizer, hcg=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._sharded = (hcg is not None and
                         hcg.get_sharding_parallel_world_size() > 1)
        if self._sharded:
            self._wrap_accumulator_creation()

    def _wrap_accumulator_creation(self):
        inner = self._inner_opt
        orig_add = inner._add_accumulator
        mesh = get_global_mesh()

        def sharded_add(name, param, fill_value=0.0, dtype=None):
            arr = orig_add(name, param, fill_value, dtype)
            if mesh is None or isinstance(arr, jax.core.Tracer):
                return arr
            # shard the largest dim over the sharding axis when divisible
            spec_axes = [None] * arr.ndim
            shard_size = mesh.shape[SHARDING_AXIS]
            for i, s in enumerate(arr.shape):
                if s % shard_size == 0 and s >= shard_size:
                    spec_axes[i] = SHARDING_AXIS
                    break
            spec = PartitionSpec(*spec_axes)
            placed = jax.device_put(arr, NamedSharding(mesh, spec))
            inner._accumulators[name][id(param)] = placed
            return placed

        inner._add_accumulator = sharded_add

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad
