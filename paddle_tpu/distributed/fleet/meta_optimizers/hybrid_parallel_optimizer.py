"""HybridParallelOptimizer (analogue of
meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:
HybridParallelClipGrad:45, HybridParallelOptimizer:265).

On the single-program SPMD model, gradients of replicated params are already
globally reduced by GSPMD, so the optimizer's distributed duties reduce to:
global-norm clipping that is correct across sharded params (sum of squares is
computed over the full logical tensors — GSPMD handles partial shards), and
delegating everything else to the inner optimizer.
"""

from __future__ import annotations

from ....nn.clip import ClipGradByGlobalNorm


class HybridParallelClipGrad(ClipGradByGlobalNorm):
    def __init__(self, clip, hcg):
        super().__init__(getattr(clip, "clip_norm", 1.0))
        self._hcg = hcg


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        inner_clip = optimizer._grad_clip
        if isinstance(inner_clip, ClipGradByGlobalNorm) and hcg is not None:
            optimizer._grad_clip = HybridParallelClipGrad(inner_clip, hcg)

    def __getattr__(self, item):
        return getattr(self.__dict__["_inner_opt"], item)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *args, **kwargs):
        return self._inner_opt.minimize(loss, *args, **kwargs)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, state):
        return self._inner_opt.set_state_dict(state)
