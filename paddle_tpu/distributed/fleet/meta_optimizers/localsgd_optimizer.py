"""LocalSGD meta-optimizer (reference:
``python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py``).

Each worker steps its inner optimizer on purely local gradients; every
``k_steps`` the parameters are averaged across the data-parallel group
(one all-reduce of params instead of per-step gradient all-reduce — the
LocalSGD communication saving).  ``begin_step`` delays the first sync,
matching the reference's warmup semantics.

On a GSPMD single-controller mesh, per-step grad sync is implicit in the
batch-axis sharding, so LocalSGD applies to the multi-process
(jax.distributed / fleet launch) layout where each process owns its
replica; ``sync_params`` uses the eager collective path.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["LocalSGDOptimizer"]


class LocalSGDOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, begin_step=1,
                 group=None):
        if k_steps < 1:
            raise ValueError(f"k_steps must be >= 1, got {k_steps}")
        self._inner = inner_optimizer
        self._k = k_steps
        self._begin = begin_step
        self._group = group
        self._step_count = 0

    @property
    def inner_optimizer(self):
        return self._inner

    def get_lr(self):
        return self._inner.get_lr()

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, state):
        return self._inner.set_state_dict(state)

    def clear_grad(self, set_to_zero=True):
        return self._inner.clear_grad(set_to_zero)

    @property
    def _parameter_list(self):
        return self._inner._parameter_list

    def _world_size(self):
        if self._group is not None:
            return getattr(self._group, "nranks",
                           getattr(self._group, "world_size", 1))
        from ... import get_world_size
        return get_world_size()

    def sync_params(self):
        """Average parameters across the replica group (all-reduce/nranks)."""
        n = self._world_size()
        if n <= 1:
            return
        from ... import all_reduce
        for p in self._inner._parameter_list:
            all_reduce(p, group=self._group)
            p._value = p._value / jnp.asarray(n, p._value.dtype)

    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._step_count >= self._begin and \
                (self._step_count - self._begin) % self._k == 0:
            self.sync_params()
