"""Deep Gradient Compression momentum optimizer (reference:
``python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py:32``
over the DGC paper's algorithm: exchange only the top-k largest-magnitude
gradient entries each step; the rest accumulate locally with momentum
correction, so convergence matches dense momentum SGD at ~0.1% of the
gradient traffic).

Per step, per parameter:

    u = m * u + g                      (local momentum accumulation)
    v = v + u                          (local gradient accumulation)
    mask = top-k(|v|)                  (k from the sparsity schedule)
    exchanged = allreduce(v * mask)    (the sparse communication)
    v, u = v * ~mask, u * ~mask        (clear what was sent)
    p = p - lr * exchanged

``rampup_begin_step``/``rampup_step``/``sparsity`` mirror the reference's
warmup schedule (dense until rampup begins, then stepping through the
sparsity list).  Communication uses the eager data plane when installed
(multi-process); single-process it is the identity, preserving exact
semantics for tests and local runs.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

__all__ = ["DGCMomentumOptimizer"]


class DGCMomentumOptimizer:
    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), parameters=None, parameter_list=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._momentum = momentum
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = list(sparsity)
        self._params = list(parameters or parameter_list or [])
        if not self._params:
            raise ValueError("DGCMomentumOptimizer needs parameters")
        self._grad_clip = grad_clip
        self._u = {}  # id -> momentum accumulation
        self._v = {}  # id -> gradient accumulation
        self._step_count = 0

    @property
    def _parameter_list(self):
        return self._params

    def get_lr(self):
        return self._lr

    def current_sparsity(self) -> float:
        """Reference rampup: 0 (dense) before rampup_begin_step, then the
        sparsity list advanced every rampup_step steps, ending at its
        final value."""
        if self._step_count < self._rampup_begin:
            return 0.0
        idx = (self._step_count - self._rampup_begin) // self._rampup_step
        return self._sparsity[min(idx, len(self._sparsity) - 1)]

    def _exchange(self, sparse_grad: np.ndarray) -> np.ndarray:
        from ...eager_comm import get_eager_comm
        plane = get_eager_comm()
        if plane is not None and plane.world > 1:
            return plane.all_reduce(sparse_grad, "avg")
        return sparse_grad

    def clear_grad(self, set_to_zero=True):
        for p in self._params:
            p.clear_grad()

    def _clip_scale(self) -> float:
        """Global-norm clip factor over all current grads (the reference
        DGC optimizer honors grad_clip before compression)."""
        if self._grad_clip is None or \
                not hasattr(self._grad_clip, "clip_norm"):
            return 1.0
        total = 0.0
        for p in self._params:
            if p.grad is not None:
                g = np.asarray(p.grad._value, np.float64)
                total += float((g * g).sum())
        norm = float(np.sqrt(total))
        cn = float(self._grad_clip.clip_norm)
        return cn / norm if norm > cn else 1.0

    def step(self):
        sparsity = self.current_sparsity()
        self._step_count += 1
        clip_scale = self._clip_scale()
        for p in self._params:
            if p.grad is None:
                continue
            g = np.asarray(p.grad._value, np.float32).reshape(-1) \
                * np.float32(clip_scale)
            key = id(p)
            u = self._u.get(key)
            v = self._v.get(key)
            if u is None:
                u = np.zeros_like(g)
                v = np.zeros_like(g)
            u = self._momentum * u + g
            v = v + u
            if sparsity <= 0.0:
                exchanged = self._exchange(v)
                v = np.zeros_like(v)
                u = np.zeros_like(u)
            else:
                k = max(1, int(round(v.size * (1.0 - sparsity))))
                thresh_idx = np.argpartition(np.abs(v), -k)[-k:]
                mask = np.zeros(v.shape, bool)
                mask[thresh_idx] = True
                exchanged = self._exchange(np.where(mask, v, 0.0))
                v = np.where(mask, 0.0, v)
                u = np.where(mask, 0.0, u)
            self._u[key] = u
            self._v[key] = v
            update = jnp.asarray(exchanged.reshape(p._value.shape),
                                 p._value.dtype)
            p._value = p._value - jnp.asarray(self._lr, p._value.dtype) \
                * update

    def _param_key(self, p, index):
        name = getattr(p, "name", None)
        return name if name else f"param_{index}"

    def state_dict(self):
        """Accumulators keyed by parameter NAME (portable across
        processes — the residuals are DGC's correctness mechanism and
        must survive checkpoint/resume)."""
        u, v = {}, {}
        for i, p in enumerate(self._params):
            key = self._param_key(p, i)
            if id(p) in self._u:
                u[key] = self._u[id(p)]
                v[key] = self._v[id(p)]
        return {"u": u, "v": v, "step": self._step_count}

    def set_state_dict(self, state):
        self._step_count = int(state.get("step", 0))
        for i, p in enumerate(self._params):
            key = self._param_key(p, i)
            if key in state.get("u", {}):
                self._u[id(p)] = np.asarray(state["u"][key], np.float32)
                self._v[id(p)] = np.asarray(state["v"][key], np.float32)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []
