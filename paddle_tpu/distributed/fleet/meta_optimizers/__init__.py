from .hybrid_parallel_optimizer import HybridParallelOptimizer
from .dygraph_sharding_optimizer import DygraphShardingOptimizer
from .localsgd_optimizer import LocalSGDOptimizer
from .dgc_optimizer import DGCMomentumOptimizer

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer",
           "LocalSGDOptimizer", "DGCMomentumOptimizer"]
