from .hybrid_parallel_optimizer import HybridParallelOptimizer
from .dygraph_sharding_optimizer import DygraphShardingOptimizer
from .localsgd_optimizer import LocalSGDOptimizer

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer",
           "LocalSGDOptimizer"]
