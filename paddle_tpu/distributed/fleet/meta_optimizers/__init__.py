from .hybrid_parallel_optimizer import HybridParallelOptimizer
from .dygraph_sharding_optimizer import DygraphShardingOptimizer

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer"]
