"""Auto-tuner for parallel configurations.

Capability analogue of ``python/paddle/distributed/auto_tuner``
(reference: auto_tuner/{tuner.py,search.py,prune.py,recorder.py}): given a
device count and model description, enumerate candidate (dp, mp, pp,
sharding-stage, micro-batch) configs, prune invalid/oversized ones, rank
by an analytic TPU cost model, and optionally measure real trials through
a user-supplied runner — recording a sorted history like the reference's
``recorder.store_history``.

TPU-native cost model: step time ≈ compute (model FLOPs / chip peak /
mp·pp·dp) + TP collective time (2·(mp-1)/mp · activation bytes / ICI bw
per layer) + PP bubble factor ((pp-1)/micro_steps) + DP gradient
all-reduce amortized — the scaling-book first-order terms, enough to
rank configs the way the reference's profile trials do.
"""

from __future__ import annotations

import csv
import itertools
import math
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = ["TunerConfig", "Candidate", "AutoTuner"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


@dataclass
class TunerConfig:
    """Search space (reference tuner_cfg keys; "auto" = search)."""

    num_devices: int = 8
    num_nodes: int = 1
    global_batch_size: int = 32
    model_size_b: float = 7.0          # parameters, billions
    hidden_size: int = 4096
    num_layers: int = 32
    seq_len: int = 4096
    dp_degree: object = "auto"
    mp_degree: object = "auto"
    pp_degree: object = "auto"
    sharding_degree: object = "auto"
    sharding_stage: object = "auto"    # 1/2/3
    micro_batch_size: object = "auto"
    chip_hbm_gb: float = 95.0          # v5p
    chip_peak_tflops: float = 459.0    # v5p bf16
    ici_gbps: float = 1200.0           # per-link bidirectional
    max_trials: int = 0                # 0 = cost-model only


@dataclass
class Candidate:
    dp: int
    mp: int
    pp: int
    sharding: int
    sharding_stage: int
    micro_batch: int
    est_step_time: float = math.inf
    est_mem_gb: float = math.inf
    measured: Optional[float] = None
    pruned: Optional[str] = None

    def as_dict(self):
        return {"dp_degree": self.dp, "mp_degree": self.mp,
                "pp_degree": self.pp, "sharding_degree": self.sharding,
                "sharding_stage": self.sharding_stage,
                "micro_batch_size": self.micro_batch,
                "est_step_time": self.est_step_time,
                "est_mem_gb": self.est_mem_gb,
                "measured": self.measured, "pruned": self.pruned}


class AutoTuner:
    def __init__(self, config: TunerConfig):
        self.cfg = config
        self.history: list[Candidate] = []

    # ------------------------------------------------------------- search
    def _axis_options(self, value, n):
        if value == "auto":
            return _divisors(n)
        return [int(value)]

    def generate_candidates(self):
        c = self.cfg
        n = c.num_devices
        cands = []
        for mp in self._axis_options(c.mp_degree, n):
            for pp in self._axis_options(c.pp_degree, n // mp if n % mp == 0
                                         else 0):
                if mp * pp > n or n % (mp * pp):
                    continue
                rest = n // (mp * pp)
                for sharding in self._axis_options(c.sharding_degree, rest):
                    if rest % sharding:
                        continue
                    dp = rest // sharding
                    if c.dp_degree != "auto" and dp != int(c.dp_degree):
                        continue
                    stages = ([1, 2, 3] if c.sharding_stage == "auto"
                              else [int(c.sharding_stage)])
                    if sharding == 1:
                        stages = [1]
                    mbs = (self._mb_options(dp * sharding)
                           if c.micro_batch_size == "auto"
                           else [int(c.micro_batch_size)])
                    for st, mb in itertools.product(stages, mbs):
                        cands.append(Candidate(dp, mp, pp, sharding, st, mb))
        return cands

    def _mb_options(self, data_ways):
        per_rank = self.cfg.global_batch_size // max(data_ways, 1)
        return [m for m in (1, 2, 4, 8, 16) if m <= max(per_rank, 1)]

    # -------------------------------------------------------------- prune
    def prune(self, cand: Candidate) -> Optional[str]:
        c = self.cfg
        data_ways = cand.dp * cand.sharding
        if c.global_batch_size % data_ways:
            return "global batch not divisible by dp*sharding"
        per_rank = c.global_batch_size // data_ways
        if per_rank % cand.micro_batch:
            return "per-rank batch not divisible by micro batch"
        if c.num_layers % cand.pp:
            return "layers not divisible by pp"
        if cand.mp > 1 and c.hidden_size % cand.mp:
            return "hidden not divisible by mp"
        cand.est_mem_gb = self._estimate_memory(cand)
        if cand.est_mem_gb > c.chip_hbm_gb:
            return f"est mem {cand.est_mem_gb:.0f}GB > HBM"
        return None

    def _estimate_memory(self, cand: Candidate) -> float:
        c = self.cfg
        p = c.model_size_b * 1e9 / (cand.mp * cand.pp)
        # bf16 weights + fp32 master + 2 fp32 moments = 18 bytes/param,
        # optimizer+master sharded by `sharding` (stage>=1), grads by
        # stage>=2, params by stage 3
        opt = 12.0 / cand.sharding
        grad = 2.0 / (cand.sharding if cand.sharding_stage >= 2 else 1)
        weight = 2.0 / (cand.sharding if cand.sharding_stage >= 3 else 1)
        states = p * (weight + grad + opt)
        # activations: micro_batch * seq * hidden * layers-per-stage * ~34B
        # (bf16, flash-attn era per-layer footprint, remat halves it)
        act = (cand.micro_batch * c.seq_len * c.hidden_size *
               (c.num_layers / cand.pp) * 34 / cand.mp) * 0.5
        return (states + act) / 1e9

    # --------------------------------------------------------- cost model
    def estimate_step_time(self, cand: Candidate) -> float:
        c = self.cfg
        flops = 6.0 * c.model_size_b * 1e9 * c.global_batch_size * c.seq_len
        chip_flops = flops / c.num_devices
        t_compute = chip_flops / (c.chip_peak_tflops * 1e12 * 0.5)
        # TP collectives: 2 all-reduces of activations per layer fwd+bwd
        if cand.mp > 1:
            act_bytes = (c.global_batch_size /
                         (cand.dp * cand.sharding)) * c.seq_len \
                * c.hidden_size * 2
            ar = 2 * (cand.mp - 1) / cand.mp * act_bytes \
                / (c.ici_gbps * 1e9 / 8)
            t_tp = 4 * c.num_layers / cand.pp * ar
        else:
            t_tp = 0.0
        # PP bubble
        micro_steps = max(
            c.global_batch_size // (cand.dp * cand.sharding *
                                    cand.micro_batch), 1)
        bubble = (cand.pp - 1) / (micro_steps + cand.pp - 1)
        # DP/sharding gradient reduce-scatter+all-gather
        p_bytes = c.model_size_b * 1e9 / (cand.mp * cand.pp) * 2
        data_ways = cand.dp * cand.sharding
        t_dp = (2 * (data_ways - 1) / data_ways * p_bytes /
                (c.ici_gbps * 1e9 / 8)) if data_ways > 1 else 0.0
        return (t_compute + t_tp) / (1 - bubble) + t_dp

    # --------------------------------------------------------------- tune
    def tune(self, runner: Callable[[Candidate], float] = None):
        """Rank all candidates; optionally measure the top max_trials with
        ``runner(candidate) -> step_time`` (reference: launching trial
        jobs).  Returns the best candidate."""
        cands = self.generate_candidates()
        for cand in cands:
            cand.pruned = self.prune(cand)
            if cand.pruned is None:
                cand.est_step_time = self.estimate_step_time(cand)
        self.history = sorted(
            cands, key=lambda x: (x.pruned is not None, x.est_step_time))
        valid = [x for x in self.history if x.pruned is None]
        if not valid:
            raise ValueError("no valid parallel config for this search "
                             "space; all candidates pruned")
        if runner is not None:
            # a supplied runner always measures: default to 3 trials when
            # max_trials was left 0 (cost-model-only is runner=None)
            trials = self.cfg.max_trials or 3
            for cand in valid[:trials]:
                cand.measured = runner(cand)
            valid.sort(key=lambda x: (x.measured is None,
                                      x.measured if x.measured is not None
                                      else x.est_step_time))
        return valid[0]

    def store_history(self, path: str):
        """CSV export (reference recorder.store_history)."""
        if not self.history:
            raise ValueError("tune() has not been run")
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(
                f, fieldnames=list(self.history[0].as_dict()))
            writer.writeheader()
            for cand in self.history:
                writer.writerow(cand.as_dict())
