"""Ring attention — context parallelism over the sequence axis.

The reference snapshot has NO ring/blockwise attention (SURVEY §2.5 "CP /
ring attention: absent"); long context is handled there via flash-attn +
Megatron-SP + the sep axis.  Here context parallelism is first-class:

- :func:`ring_attention` — blockwise attention with the K/V shards rotating
  around the mesh ring via ``lax.ppermute`` (ICI neighbor hops), accumulating
  the softmax online (streaming m/l/acc, flash-attention style) so the full
  sequence is never materialized on one device.
- :func:`ulysses_attention` — the all-to-all alternative (DeepSpeed-Ulysses
  style): seq-sharded activations swap to head-sharded for exact attention,
  expressed as sharding constraints so GSPMD emits the all-to-alls over the
  "sep" axis (the reference's sep-axis consumers live downstream; here the
  consumer is in-tree).

Both run inside the same mesh as DP/TP (shard_map manual over the context
axis, auto elsewhere).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

SEQ_AXIS = "sep"


def _block_attend(q, k, v, scale, mask):
    """One block: returns (unnormalized acc, running max m, running sum l).

    q is f32; k/v arrive in the ring dtype (e.g. bf16) and are promoted to
    f32 only here, so the ppermute hops move half the bytes while the
    softmax accumulation stays full precision.
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    m = jnp.max(logits, axis=-1)                         # [B,H,Q]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)                              # [B,H,Q]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     preferred_element_type=jnp.float32)  # [B,Q,H,D]
    return acc, m, l


def ring_attention(query, key, value, mesh: Optional[Mesh] = None,
                   axis: str = SEQ_AXIS, causal: bool = True,
                   scale: Optional[float] = None):
    """Exact attention over a seq-sharded batch via K/V ring rotation.

    query/key/value: GLOBAL logical [B, S, H, D] arrays (sharded over
    ``axis`` on dim 1 by the caller or by GSPMD).  Returns [B, S, H, D].
    """
    from .topology import get_global_mesh
    mesh = mesh or get_global_mesh()
    d = query.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    n = mesh.shape[axis]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def inner(q, k, v):
        # local shards: [B, S/n, H, D]
        my = jax.lax.axis_index(axis)
        s_local = q.shape[1]
        q_pos = my * s_local + jnp.arange(s_local)       # global q positions

        def step(carry, t):
            k_t, v_t, m_run, l_run, acc = carry
            kv_rank = (my - t) % n                       # whose shard we hold
            if causal:
                k_pos = kv_rank * s_local + jnp.arange(s_local)
                mask = q_pos[:, None] >= k_pos[None, :]  # [Q, K]
                mask = mask[None, None, :, :]
            else:
                mask = None
            blk_acc, blk_m, blk_l = _block_attend(qf, k_t, v_t, s, mask)
            new_m = jnp.maximum(m_run, blk_m)
            alpha = jnp.exp(m_run - new_m)
            beta = jnp.exp(blk_m - new_m)
            l_new = l_run * alpha + blk_l * beta
            acc_new = acc * alpha.transpose(0, 2, 1)[..., None] + \
                blk_acc * beta.transpose(0, 2, 1)[..., None]
            k_nxt = jax.lax.ppermute(k_t, axis, perm)
            v_nxt = jax.lax.ppermute(v_t, axis, perm)
            return (k_nxt, v_nxt, new_m, l_new, acc_new), None

        from .topology import pvary as _pvary
        b, _, h, dd = q.shape
        m0 = jnp.full((b, h, s_local), -1e30, jnp.float32)
        l0 = jnp.zeros((b, h, s_local), jnp.float32)
        acc0 = jnp.zeros((b, s_local, h, dd), jnp.float32)
        m0 = _pvary(m0, (axis,))
        l0 = _pvary(l0, (axis,))
        acc0 = _pvary(acc0, (axis,))
        qf = q.astype(jnp.float32)  # q never rotates; promote once
        (_, _, m_fin, l_fin, acc_fin), _ = jax.lax.scan(
            step, (k, v, m0, l0, acc0), jnp.arange(n))
        out = acc_fin / jnp.maximum(
            l_fin.transpose(0, 2, 1)[..., None], 1e-30)
        return out.astype(q.dtype)

    spec = PartitionSpec(None, axis, None, None)
    sm = jax.shard_map(inner, mesh=mesh,
                       in_specs=(spec, spec, spec),
                       out_specs=spec,
                       axis_names={axis})
    return sm(query, key, value)


def ulysses_attention(query, key, value, mesh: Optional[Mesh] = None,
                      axis: str = SEQ_AXIS, causal: bool = True,
                      scale: Optional[float] = None):
    """All-to-all sequence parallelism: constrain seq-sharded -> head-sharded
    around an exact attention; GSPMD emits the two all-to-alls."""
    from .topology import get_global_mesh
    mesh = mesh or get_global_mesh()
    d = query.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)

    def constrain(x, spec):
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    seq_spec = PartitionSpec(None, axis, None, None)
    head_spec = PartitionSpec(None, None, axis, None)

    q = constrain(query, head_spec)   # a2a: seq-shard -> head-shard
    k = constrain(key, head_spec)
    v = constrain(value, head_spec)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(mask, logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return constrain(out.astype(query.dtype), seq_spec)  # a2a back
