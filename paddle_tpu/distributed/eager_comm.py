"""Eager (outside-compiled-region) collectives over the native TCPStore —
the Gloo-style data plane of the reference
(``python/paddle/distributed/communication/all_reduce.py`` working eagerly
through ProcessGroupGloo/NCCL).

On TPU the high-performance path is always the compiled XLA collective;
this store-backed plane exists for the reference's eager semantics:
multi-process host-side coordination, debugging runs, small-tensor
synchronization (e.g. LocalSGD parameter averaging), and CPU CI.  Every
rank posts its buffer under a sequence-numbered key and reads its peers'
— O(world^2) traffic through the store server, correct and simple, not a
throughput path (the reference's Gloo backend has the same shape).
"""

from __future__ import annotations

import os
import pickle
import threading

import numpy as np

__all__ = ["EagerComm", "get_eager_comm", "init_eager_comm"]

_comm = None
_lock = threading.Lock()


class EagerComm:
    def __init__(self, store, rank: int, world: int, prefix: str = "ec"):
        self.store = store
        self.rank = rank
        self.world = world
        self.prefix = prefix
        self._seq = 0

    def _key(self, seq, rank, tag=""):
        return f"{self.prefix}/{seq}{tag}/{rank}"

    def _next(self):
        self._seq += 1
        return self._seq

    # -- primitives -----------------------------------------------------
    def _post_and_collect(self, payload: bytes, seq, tag="") -> list:
        self.store.set(self._key(seq, self.rank, tag), payload)
        out = []
        for r in range(self.world):
            out.append(self.store.get(self._key(seq, r, tag)))
        # GC: the LAST rank to finish reading tombstones the payloads
        # (1-byte markers); without it a long run accumulates every
        # historical buffer in the store server
        done = self.store.add(f"{self.prefix}/done/{seq}{tag}", 1)
        if done == self.world:
            for r in range(self.world):
                self.store.set(self._key(seq, r, tag), b"\0")
        return out

    def all_reduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        seq = self._next()
        arr = np.ascontiguousarray(array)
        blobs = self._post_and_collect(
            pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes())), seq)
        acc = None
        for blob in blobs:
            dt, shape, raw = pickle.loads(blob)
            peer = np.frombuffer(raw, np.dtype(dt)).reshape(shape)
            if acc is None:
                acc = peer.astype(np.float64) \
                    if np.issubdtype(peer.dtype, np.floating) else \
                    peer.copy()
            elif op in ("sum", "avg"):
                acc = acc + peer
            elif op == "max":
                acc = np.maximum(acc, peer)
            elif op == "min":
                acc = np.minimum(acc, peer)
            elif op == "prod":
                acc = acc * peer
            else:
                raise ValueError(f"unsupported reduce op {op!r}")
        if op == "avg":
            acc = acc / self.world
        return np.asarray(acc, arr.dtype)

    def all_gather(self, array: np.ndarray) -> list:
        seq = self._next()
        arr = np.ascontiguousarray(array)
        blobs = self._post_and_collect(
            pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes())), seq)
        out = []
        for blob in blobs:
            dt, shape, raw = pickle.loads(blob)
            out.append(np.frombuffer(raw, np.dtype(dt)).reshape(shape)
                       .copy())
        return out

    def all_gather_object(self, obj) -> list:
        seq = self._next()
        blobs = self._post_and_collect(pickle.dumps(obj), seq, tag="o")
        return [pickle.loads(b) for b in blobs]

    def broadcast(self, array: np.ndarray, src: int) -> np.ndarray:
        seq = self._next()
        if self.rank == src:
            arr = np.ascontiguousarray(array)
            self.store.set(self._key(seq, src, "b"),
                           pickle.dumps((arr.dtype.str, arr.shape,
                                         arr.tobytes())))
        blob = self.store.get(self._key(seq, src, "b"))
        dt, shape, raw = pickle.loads(blob)
        done = self.store.add(f"{self.prefix}/done/{seq}b", 1)
        if done == self.world:
            self.store.set(self._key(seq, src, "b"), b"\0")
        return np.frombuffer(raw, np.dtype(dt)).reshape(shape).copy()

    def send(self, array: np.ndarray, dst: int, tag: int = 0):
        # per-pair store counters sequence repeated sends under one tag
        # (matching call order on both sides), so no message is lost or
        # read twice
        idx = self.store.add(
            f"{self.prefix}/p2ps/{self.rank}->{dst}/{tag}", 1)
        arr = np.ascontiguousarray(array)
        self.store.set(f"{self.prefix}/p2p/{self.rank}->{dst}/{tag}/{idx}",
                       pickle.dumps((arr.dtype.str, arr.shape,
                                     arr.tobytes())))

    def recv(self, src: int, tag: int = 0) -> np.ndarray:
        idx = self.store.add(
            f"{self.prefix}/p2pr/{src}->{self.rank}/{tag}", 1)
        key = f"{self.prefix}/p2p/{src}->{self.rank}/{tag}/{idx}"
        blob = self.store.get(key)
        dt, shape, raw = pickle.loads(blob)
        self.store.set(key, b"\0")  # GC the payload
        return np.frombuffer(raw, np.dtype(dt)).reshape(shape).copy()

    def barrier(self):
        seq = self._next()
        n = self.store.add(f"{self.prefix}/bar/{seq}", 1)
        while n < self.world:
            import time
            time.sleep(0.002)
            n = self.store.add(f"{self.prefix}/bar/{seq}", 0)


def init_eager_comm(store=None, rank=None, world=None):
    """Install the eager data plane.  Without arguments, bootstraps from
    the launcher env (MASTER_ADDR + PADDLE_EAGER_STORE_PORT, rank 0 hosts
    the store server)."""
    global _comm
    with _lock:
        if store is not None:
            from .env import get_rank, get_world_size
            _comm = EagerComm(store,
                              get_rank() if rank is None else rank,
                              get_world_size() if world is None else world)
            return _comm
        from .env import get_rank, get_world_size
        rank = get_rank() if rank is None else rank
        world = get_world_size() if world is None else world
        if world <= 1:
            _comm = None
            return None
        from ..runtime import TCPStore, TCPStoreServer
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = int(os.environ.get(
            "PADDLE_EAGER_STORE_PORT",
            int(os.environ.get("MASTER_PORT", "8787")) + 17))
        if rank == 0:
            server = TCPStoreServer(port)
            if server.port != port:
                # Non-zero ranks dial the env-derived port; silently binding
                # elsewhere would strand them. Fail fast on rank 0 instead.
                bound = server.port
                try:
                    server.stop()
                except Exception:
                    pass
                raise RuntimeError(
                    f"eager-comm store port {port} is busy (server bound "
                    f"{bound}); set PADDLE_EAGER_STORE_PORT to a free "
                    "port on every rank")
            _comm_server_keepalive.append(server)
        client = TCPStore(addr, port)
        _comm = EagerComm(client, rank, world)
        return _comm


_comm_server_keepalive: list = []


def get_eager_comm():
    return _comm
