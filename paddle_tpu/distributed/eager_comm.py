"""Eager (outside-compiled-region) collectives — the Gloo-style data
plane of the reference
(``python/paddle/distributed/communication/all_reduce.py`` working eagerly
through ProcessGroupGloo/NCCL).

Two transports:

- **XLA-backed** (preferred, auto-selected when ``jax.distributed`` is
  initialized and spans this world): array collectives run through
  ``jax.experimental.multihost_utils`` — compiled allgather/psum over the
  real interconnect with tree algorithms, O(world) per-rank traffic.
  This is the scaling path (reference ProcessGroupNCCL's eager role).
- **store relay** (fallback: no jax.distributed, or send/recv/objects):
  every rank posts its buffer under a sequence-numbered key on the
  native TCPStore and reads its peers' — O(world^2) through the store
  server, correct and simple (the reference's Gloo-over-store shape);
  fine for bootstrap and CI, not a throughput path.
"""

from __future__ import annotations

import os
import pickle
import threading

import numpy as np

__all__ = ["EagerComm", "get_eager_comm", "init_eager_comm"]

_comm = None
_lock = threading.Lock()


def _xla_world_available(world: int) -> bool:
    try:
        import jax
        return jax.process_count() == world and world > 1
    except Exception:
        return False


class EagerComm:
    def __init__(self, store, rank: int, world: int, prefix: str = "ec",
                 use_xla=None):
        self.store = store
        self.rank = rank
        self.world = world
        self.prefix = prefix
        self._seq = 0
        if use_xla is not None:
            self.use_xla = bool(use_xla)
        elif world <= 1:
            self.use_xla = False
        else:
            # transport AGREEMENT round: each rank's local view (jax
            # distributed up AND its jax process index == its comm rank)
            # is posted through the store; XLA is used only when every
            # rank can — a per-process decision could split the world
            # across transports and deadlock the next collective.  Keys
            # are scoped by this rank's construction COUNT so
            # re-initialization (e.g. before vs after
            # jax.distributed.initialize) reads the matching round,
            # never a stale vote; matched construction order across
            # ranks is the same contract the collectives already
            # require.  A store failure here must RAISE — a silent
            # fallback on one rank would split transports.
            local_ok = _xla_world_available(world) and self._rank_is_jax()
            epoch = self.store.add(f"{prefix}/xla_round/{rank}", 1)
            self.store.set(f"{prefix}/xla_ok/{epoch}/{rank}",
                           b"1" if local_ok else b"0")
            self.use_xla = all(
                self.store.get(f"{prefix}/xla_ok/{epoch}/{r}") == b"1"
                for r in range(world))

    def _rank_is_jax(self) -> bool:
        try:
            import jax
            return jax.process_index() == self.rank
        except Exception:
            return False

    def _key(self, seq, rank, tag=""):
        return f"{self.prefix}/{seq}{tag}/{rank}"

    def _next(self):
        self._seq += 1
        return self._seq

    # -- XLA transport (multi-process jax.distributed) ------------------
    def _xla_ok(self) -> bool:
        # use_xla was AGREED across the world at init (see __init__);
        # a per-call re-check could diverge between ranks and deadlock
        return self.use_xla

    def _xla_allgather(self, array: np.ndarray) -> np.ndarray:
        """[world, ...] gathered along a new leading axis — ONE compiled
        allgather over the interconnect (tree algorithm), O(world)
        per-rank traffic instead of the store relay's O(world^2)."""
        from jax.experimental import multihost_utils
        return np.asarray(
            multihost_utils.process_allgather(
                np.ascontiguousarray(array)))

    # -- primitives -----------------------------------------------------
    def _post_and_collect(self, payload: bytes, seq, tag="") -> list:
        self.store.set(self._key(seq, self.rank, tag), payload)
        out = []
        for r in range(self.world):
            out.append(self.store.get(self._key(seq, r, tag)))
        # GC: the LAST rank to finish reading tombstones the payloads
        # (1-byte markers); without it a long run accumulates every
        # historical buffer in the store server
        done = self.store.add(f"{self.prefix}/done/{seq}{tag}", 1)
        if done == self.world:
            for r in range(self.world):
                self.store.set(self._key(seq, r, tag), b"\0")
        return out

    def all_reduce(self, array: np.ndarray, op: str = "sum") -> np.ndarray:
        if self._xla_ok():
            g = self._xla_allgather(array)
            if np.issubdtype(g.dtype, np.floating):
                g = g.astype(np.float64)
            if op in ("sum", "avg"):
                acc = g.sum(axis=0)
                if op == "avg":
                    acc = acc / self.world
            elif op == "max":
                acc = g.max(axis=0)
            elif op == "min":
                acc = g.min(axis=0)
            elif op == "prod":
                acc = g.prod(axis=0)
            else:
                raise ValueError(f"unsupported reduce op {op!r}")
            return np.asarray(acc, np.asarray(array).dtype)
        seq = self._next()
        arr = np.ascontiguousarray(array)
        blobs = self._post_and_collect(
            pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes())), seq)
        acc = None
        for blob in blobs:
            dt, shape, raw = pickle.loads(blob)
            peer = np.frombuffer(raw, np.dtype(dt)).reshape(shape)
            if acc is None:
                acc = peer.astype(np.float64) \
                    if np.issubdtype(peer.dtype, np.floating) else \
                    peer.copy()
            elif op in ("sum", "avg"):
                acc = acc + peer
            elif op == "max":
                acc = np.maximum(acc, peer)
            elif op == "min":
                acc = np.minimum(acc, peer)
            elif op == "prod":
                acc = acc * peer
            else:
                raise ValueError(f"unsupported reduce op {op!r}")
        if op == "avg":
            acc = acc / self.world
        return np.asarray(acc, arr.dtype)

    def all_gather(self, array: np.ndarray) -> list:
        if self._xla_ok():
            g = self._xla_allgather(array)
            return [g[r].copy() for r in range(self.world)]
        seq = self._next()
        arr = np.ascontiguousarray(array)
        blobs = self._post_and_collect(
            pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes())), seq)
        out = []
        for blob in blobs:
            dt, shape, raw = pickle.loads(blob)
            out.append(np.frombuffer(raw, np.dtype(dt)).reshape(shape)
                       .copy())
        return out

    def all_gather_object(self, obj) -> list:
        seq = self._next()
        blobs = self._post_and_collect(pickle.dumps(obj), seq, tag="o")
        return [pickle.loads(b) for b in blobs]

    def broadcast(self, array: np.ndarray, src: int) -> np.ndarray:
        if self._xla_ok():
            from jax.experimental import multihost_utils
            return np.asarray(multihost_utils.broadcast_one_to_all(
                np.ascontiguousarray(array),
                is_source=self.rank == src))
        seq = self._next()
        if self.rank == src:
            arr = np.ascontiguousarray(array)
            self.store.set(self._key(seq, src, "b"),
                           pickle.dumps((arr.dtype.str, arr.shape,
                                         arr.tobytes())))
        blob = self.store.get(self._key(seq, src, "b"))
        dt, shape, raw = pickle.loads(blob)
        done = self.store.add(f"{self.prefix}/done/{seq}b", 1)
        if done == self.world:
            self.store.set(self._key(seq, src, "b"), b"\0")
        return np.frombuffer(raw, np.dtype(dt)).reshape(shape).copy()

    def send(self, array: np.ndarray, dst: int, tag: int = 0):
        # per-pair store counters sequence repeated sends under one tag
        # (matching call order on both sides), so no message is lost or
        # read twice
        idx = self.store.add(
            f"{self.prefix}/p2ps/{self.rank}->{dst}/{tag}", 1)
        arr = np.ascontiguousarray(array)
        self.store.set(f"{self.prefix}/p2p/{self.rank}->{dst}/{tag}/{idx}",
                       pickle.dumps((arr.dtype.str, arr.shape,
                                     arr.tobytes())))

    def recv(self, src: int, tag: int = 0) -> np.ndarray:
        idx = self.store.add(
            f"{self.prefix}/p2pr/{src}->{self.rank}/{tag}", 1)
        key = f"{self.prefix}/p2p/{src}->{self.rank}/{tag}/{idx}"
        blob = self.store.get(key)
        dt, shape, raw = pickle.loads(blob)
        self.store.set(key, b"\0")  # GC the payload
        return np.frombuffer(raw, np.dtype(dt)).reshape(shape).copy()

    def barrier(self):
        if self._xla_ok():
            from jax.experimental import multihost_utils
            self._seq += 1
            multihost_utils.sync_global_devices(
                f"{self.prefix}/bar/{self._seq}")
            return
        seq = self._next()
        n = self.store.add(f"{self.prefix}/bar/{seq}", 1)
        while n < self.world:
            import time
            time.sleep(0.002)
            n = self.store.add(f"{self.prefix}/bar/{seq}", 0)


def init_eager_comm(store=None, rank=None, world=None):
    """Install the eager data plane.  Without arguments, bootstraps from
    the launcher env (MASTER_ADDR + PADDLE_EAGER_STORE_PORT, rank 0 hosts
    the store server)."""
    global _comm
    with _lock:
        if store is not None:
            from .env import get_rank, get_world_size
            _comm = EagerComm(store,
                              get_rank() if rank is None else rank,
                              get_world_size() if world is None else world)
            return _comm
        from .env import get_rank, get_world_size
        rank = get_rank() if rank is None else rank
        world = get_world_size() if world is None else world
        if world <= 1:
            _comm = None
            return None
        from ..runtime import TCPStore, TCPStoreServer
        addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
        port = int(os.environ.get(
            "PADDLE_EAGER_STORE_PORT",
            int(os.environ.get("MASTER_PORT", "8787")) + 17))
        if rank == 0:
            server = TCPStoreServer(port)
            if server.port != port:
                # Non-zero ranks dial the env-derived port; silently binding
                # elsewhere would strand them. Fail fast on rank 0 instead.
                bound = server.port
                try:
                    server.stop()
                except Exception:
                    pass
                raise RuntimeError(
                    f"eager-comm store port {port} is busy (server bound "
                    f"{bound}); set PADDLE_EAGER_STORE_PORT to a free "
                    "port on every rank")
            _comm_server_keepalive.append(server)
        client = TCPStore(addr, port)
        _comm = EagerComm(client, rank, world)
        return _comm


_comm_server_keepalive: list = []


def get_eager_comm():
    return _comm
