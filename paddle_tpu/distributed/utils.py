"""Distributed utilities: recompute (activation checkpointing).

Analogue of ``python/paddle/distributed/fleet/recompute/recompute.py``
(RecomputeFunction:88).  TPU-native: ``jax.checkpoint`` (rematerialization)
replaces the PyLayer replay machinery — RNG state is handled by the
counter-based PRNG automatically (same key derivation in both passes), which
is exactly what the reference's RNG-state tracker reconstructs by hand.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core import tape as _tape
from ..core.dispatch import dispatch, set_param_tracker
from ..core.tensor import Tensor

__all__ = ["recompute", "recompute_sequential"]


def _resolve_policy(policy):
    """Map a policy name to a jax.checkpoint rematerialization policy.

    "dots" (dots_with_no_batch_dims_saveable) is the sweet spot for
    transformer blocks: weight-matmul outputs are saved, attention
    score/AV matmuls and all elementwise ops are recomputed — near-zero
    extra matmul FLOPs for a fraction of full-remat's activation memory.
    """
    if policy is None or callable(policy):
        return policy
    policies = {
        "full": None,  # save nothing, recompute everything (default)
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "everything_saveable": jax.checkpoint_policies.everything_saveable,
        # save ONLY the attention outputs (tagged via checkpoint_name in
        # the attention layers): backward skips re-running the flash
        # forward while everything else still remats — +67 MB/layer at
        # bench scale vs "dots"'s ~700 MB/layer (OOM at 16 layers)
        "save_attn": jax.checkpoint_policies.save_only_these_names(
            "attn_out"),
        # additionally save the MLP gate/up projections (+536 MB/layer at
        # bench scale): backward skips the two [hidden, intermediate]
        # matmul recomputes — apply via recompute_policy_stride/_alt to
        # the layer subset that fits HBM
        "save_attn_mlp": jax.checkpoint_policies.save_only_these_names(
            "attn_out", "mlp_gate_up"),
    }
    if policy not in policies:
        raise ValueError(
            f"recompute: unknown policy {policy!r}; one of {list(policies)}")
    return policies[policy]


def recompute(function, *args, **kwargs):
    """Run ``function(*args)`` with activation rematerialization.

    Under the eager tape: the recorded vjp closes over a
    ``jax.checkpoint``-wrapped callable, so residuals are dropped and the
    forward re-runs (on-device) during backward.
    """
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    policy = _resolve_policy(kwargs.pop("policy", None))

    # discover parameters the function uses so grads flow to them
    store = {}
    set_param_tracker(store)
    try:
        with _tape.no_grad():
            probe_out = function(*args, **kwargs)
    finally:
        set_param_tracker(None)
    params = list(store.values())

    tensor_args = [a for a in args if isinstance(a, Tensor)]
    arg_slots = [i for i, a in enumerate(args) if isinstance(a, Tensor)]

    from ..core import generator as _generator
    rng_key = _generator.default_generator().next_key()

    n_params = len(params)

    @functools.partial(jax.checkpoint, policy=policy)
    def pure(rng, *arrays):
        p_arrays = arrays[:n_params]
        in_arrays = arrays[n_params:]
        saved = [p._value for p in params]
        _generator.push_trace_key(rng)
        try:
            for p, a in zip(params, p_arrays):
                p._value = a
            full_args = list(args)
            for slot, arr in zip(arg_slots, in_arrays):
                full_args[slot] = Tensor(arr)
            with _tape.no_grad():
                out = function(*full_args, **kwargs)
        finally:
            for p, s in zip(params, saved):
                p._value = s
            _generator.pop_trace_key()
        outs = out if isinstance(out, tuple) else (out,)
        return tuple(o._value if isinstance(o, Tensor) else o for o in outs)

    def impl(*arrays):
        return pure(rng_key, *arrays)

    out = dispatch("recompute", impl, tuple(params) + tuple(tensor_args))
    if isinstance(probe_out, tuple):
        return out if isinstance(out, tuple) else (out,)
    return out[0] if isinstance(out, tuple) and not isinstance(probe_out, tuple) \
        else out


def recompute_sequential(ctx, functions, *args, **kwargs):
    """Checkpoint a Sequential in segments (reference recompute_sequential:508)."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    if segments <= 1:
        def run_all(*a):
            out = a if len(a) > 1 else a[0]
            for l in layers:
                out = l(out)
            return out
        return recompute(run_all, *args, **kwargs)
    seg_size = (len(layers) + segments - 1) // segments
    out = args if len(args) > 1 else args[0]
    for s in range(0, len(layers), seg_size):
        chunk = layers[s:s + seg_size]

        def run_chunk(x, _chunk=chunk):
            for l in _chunk:
                x = l(x)
            return x

        out = recompute(run_chunk, out, **kwargs)
    return out
