"""Launcher (analogue of `python -m paddle.distributed.launch`,
reference python/paddle/distributed/launch/main.py:18).

On TPU, one process per *host* drives all local chips (SPMD), so the
launcher's job is multi-host process start + env contract, not per-GPU
spawning.  Single-host: run the script in-process.  Multi-host: the operator
runs this CLI on each host with PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
MASTER_ADDR set (same contract as the reference's collective controller).
"""

from __future__ import annotations

import os
import runpy
import sys


def launch():
    argv = sys.argv[1:]
    # parse minimal flags: --nnodes, --master, --rank, then script + args
    nnodes = 1
    master = None
    rank = 0
    script_idx = 0
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--nnodes"):
            nnodes = int(a.split("=", 1)[1] if "=" in a else argv[i + 1])
            i += 1 if "=" in a else 2
            continue
        if a.startswith("--master"):
            master = a.split("=", 1)[1] if "=" in a else argv[i + 1]
            i += 1 if "=" in a else 2
            continue
        if a.startswith("--rank"):
            rank = int(a.split("=", 1)[1] if "=" in a else argv[i + 1])
            i += 1 if "=" in a else 2
            continue
        script_idx = i
        break
    script = argv[script_idx]
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(nnodes))
    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank))
    if master:
        addr, _, port = master.partition(":")
        os.environ.setdefault("MASTER_ADDR", addr)
        os.environ.setdefault("MASTER_PORT", port or "8787")
    sys.argv = argv[script_idx:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    launch()
