"""Hybrid-parallel topology -> jax device Mesh.

Analogue of ``python/paddle/distributed/fleet/base/topology.py``
(CommunicateTopology:60, HybridCommunicateGroup:173).  The reference builds
NCCL groups for every axis combination of the 5-axis order
``["data", "pipe", "sharding", "sep", "model"]``; here the same axes become
named axes of ONE ``jax.sharding.Mesh`` and "groups" become axis names used
in sharding annotations / shard_map collectives — GSPMD then materializes
the communicators (SURVEY §7 architecture mapping).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# canonical axis order, matching topology.py:63
AXIS_ORDER = ["data", "pipe", "sharding", "sep", "model"]

_global_mesh: Optional[Mesh] = None


def build_mesh(dp: int = 1, pp: int = 1, sharding: int = 1, sep: int = 1,
               mp: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = dp * pp * sharding * sep * mp
    if need != len(devices):
        raise ValueError(
            f"topology {dp}x{pp}x{sharding}x{sep}x{mp}={need} does not match "
            f"{len(devices)} devices")
    arr = np.array(devices).reshape(dp, pp, sharding, sep, mp)
    return Mesh(arr, AXIS_ORDER)


def set_global_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return _global_mesh


def pvary(x, axes):
    """Mark x as varying over manual mesh axes (pcast on new jax, pvary on
    old); idempotent — already-varying values pass through.  Shared by the
    shard_map-based engines (pipeline, ring attention)."""
    try:
        return jax.lax.pcast(x, axes, to="varying")
    except (AttributeError, TypeError):
        return jax.lax.pvary(x, axes)
    except ValueError as e:
        if "from=varying" in str(e):
            return x
        raise


class CommunicateTopology:
    """Rank <-> coordinate arithmetic (reference CommunicateTopology:60)."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or AXIS_ORDER)
        self._dims = list(dims or [1] * len(self._parallel_names))
        self.coordinate = list(itertools.product(
            *[range(d) for d in self._dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self.coordinate[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank groups along ``axis_name`` (reference get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        other_dims = [range(d) for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for other in itertools.product(*other_dims):
            ranks = []
            for v in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, v)
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class _AxisGroup:
    """A logical communication group = a mesh axis (or fused axes)."""

    def __init__(self, axes, topo: CommunicateTopology, rank_in_group, ranks):
        self.axes = tuple(axes) if isinstance(axes, (list, tuple)) else (axes,)
        self.rank = rank_in_group
        self.ranks = ranks
        self.nranks = len(ranks)

    @property
    def axis_name(self):
        return self.axes if len(self.axes) > 1 else self.axes[0]

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"AxisGroup(axes={self.axes}, nranks={self.nranks})"


class HybridCommunicateGroup:
    """Reference HybridCommunicateGroup:173 — axis bookkeeping + Mesh owner.

    On TPU the device-level axes live in one Mesh; each get_*_group returns
    an _AxisGroup whose ``axes`` name is usable in shard_map collectives and
    PartitionSpecs.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 dp=1, pp=1, sharding=1, sep=1, mp=1):
        if topology is not None:
            dims = [topology.get_dim(n) for n in AXIS_ORDER]
            dp, pp, sharding, sep, mp = dims
        self._topo = topology or CommunicateTopology(AXIS_ORDER,
                                                     [dp, pp, sharding, sep, mp])
        self.nranks = self._topo.world_size()
        self.global_rank = 0  # single-controller SPMD: logical rank 0
        self._dp_degree = dp
        self._pp_degree = pp
        self._sharding_degree = sharding
        self._sep_degree = sep
        self._mp_degree = mp
        n_local = len(jax.devices())
        if self.nranks == n_local:
            self.mesh = build_mesh(dp, pp, sharding, sep, mp)
            set_global_mesh(self.mesh)
        else:
            self.mesh = None  # multi-host meshes built by the launcher

    def _group(self, axis):
        coord = self._topo.get_coord(self.global_rank)
        idx = AXIS_ORDER.index(axis) if isinstance(axis, str) else None
        if isinstance(axis, str):
            ranks = [r for r in self._topo.get_comm_list(axis)
                     if self.global_rank in r][0]
            return _AxisGroup(axis, self._topo, ranks.index(self.global_rank),
                              ranks)
        # fused axes
        names = list(axis)
        all_ranks = list(range(self.nranks))

        def key(r):
            c = self._topo.get_coord(r)
            return tuple(v for i, v in enumerate(c)
                         if AXIS_ORDER[i] not in names)

        mykey = key(self.global_rank)
        ranks = [r for r in all_ranks if key(r) == mykey]
        return _AxisGroup(tuple(names), self._topo,
                          ranks.index(self.global_rank), ranks)

    # ---- parallel info (reference API surface) ----
    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1 and self._dp_degree == 1 and \
                self._mp_degree == 1:
            return "sharding_parallel"
        if self._mp_degree > 1:
            return "model_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[0]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._group("data")

    def get_data_parallel_group_src_rank(self):
        return self.get_data_parallel_group().ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[4]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._group("model")

    def get_model_parallel_group_src_rank(self):
        return self.get_model_parallel_group().ranks[0]

    # pipeline
    def get_stage_id(self):
        return self._topo.get_coord(self.global_rank)[1]

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._group("pipe")

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[2]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_sharding_parallel_group_src_rank(self):
        return self.get_sharding_parallel_group().ranks[0]

    # sep (Ulysses sequence axis; reference topology.py:216-237)
    def get_sep_parallel_rank(self):
        return self._topo.get_coord(self.global_rank)[3]

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_dp_sep_parallel_group(self):
        return self._group(("data", "sep"))

    def get_pp_mp_parallel_group(self):
        return self._group(("pipe", "model"))

    # check groups (sanity sets, reference get_check_parallel_group)
    def get_check_parallel_group(self, sharding_new_group=False):
        return self._group(("pipe", "sharding", "sep", "model"))

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)


_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg
