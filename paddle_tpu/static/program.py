"""Static-graph mode: Program capture + compiled Executor.

TPU-native redesign of the reference's static stack (SURVEY §2.2/§3.2:
ProgramDesc ⊃ Blocks ⊃ Ops; ``exe.run`` → ``_ExecutorCache`` →
``StandaloneExecutor`` → instruction DAG on a workqueue). Here the IR *is*
the captured op DAG, and the executor is XLA:

- ``static.data(name, shape, dtype)`` creates a feed Variable (a symbolic
  Tensor holding an aval, no storage).
- under ``program_guard`` every op that flows through the eager dispatcher
  is recorded into the Program instead of executing (out-avals via
  ``jax.eval_shape`` ≙ InferMeta); concrete Tensors crossing into the graph
  become parameters/constants of the program (≙ persistable vars in Scope).
- ``Executor.run(program, feed=…, fetch_list=…)`` replays the DAG as one
  pure jax function, jit-compiles it per (program, feed-signature) — the
  whole Program is ONE fused XLA executable, the TPU-correct analogue of
  the instruction-by-instruction interpreter — and caches it (≙
  _ExecutorCache at executor.py:816).
- ``append_backward(loss)`` marks gradient outputs computed by ``jax.grad``
  over the same replay (≙ base/backward.py's grad-op construction).
- ``Optimizer.minimize(loss)`` in static mode records functional parameter
  updates executed inside the same compiled program; updated values are
  written back to the parameter tensors after each run (≙ optimizer ops +
  Scope mutation).
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch_mod
from ..core.dtypes import convert_dtype
from ..core.tensor import Tensor

_var_ids = itertools.count()


def _symbolic_tensor(aval, name=None) -> Tensor:
    """A Tensor with no storage: `_value` is a ShapeDtypeStruct. Shape/dtype
    queries work; any attempt to read data raises, like an uninitialized
    static Variable in the reference."""
    t = Tensor.__new__(Tensor)
    t._value = aval  # jax.ShapeDtypeStruct quacks shape/dtype
    t.stop_gradient = True
    t._grad = None
    t._node = None
    t._out_index = 0
    t._grad_hooks = []
    t.name = name or f"var_{next(_var_ids)}"
    t.persistable = False
    t._is_param = False
    t._dist_attr = None
    return t


class _OpRecord:
    __slots__ = ("op_name", "impl", "inputs", "n_outputs", "out_ids")

    def __init__(self, op_name, impl, inputs, n_outputs, out_ids):
        self.op_name = op_name
        self.impl = impl          # pure fn over jax arrays (attrs closed over)
        self.inputs = inputs      # list of ("var", id) | ("const", key)
        self.n_outputs = n_outputs
        self.out_ids = out_ids


class Program:
    """Captured op DAG (≙ ProgramDesc, framework.proto:267)."""

    def __init__(self):
        self.ops: List[_OpRecord] = []
        self.feeds: Dict[str, int] = {}       # feed name -> var id
        self.var_avals: Dict[int, jax.ShapeDtypeStruct] = {}
        self.var_names: Dict[int, str] = {}
        # concrete tensors captured by the graph (params + constants):
        self.captured: Dict[int, Tensor] = {}  # key=id(tensor)
        self.grad_of: Dict[int, Tensor] = {}   # grad var id -> param tensor
        self._loss_var: Optional[int] = None
        self.updates: List = []   # (param, new_value_var_id)
        self.version = 0

    # -- building --
    def add_feed(self, name, shape, dtype) -> Tensor:
        aval = jax.ShapeDtypeStruct(tuple(shape), convert_dtype(dtype))
        t = _symbolic_tensor(aval, name)
        vid = next(_var_ids)
        t._static_var_id = vid
        self.feeds[name] = vid
        self.var_avals[vid] = aval
        self.var_names[vid] = name
        self.version += 1
        return t

    def record(self, op_name, impl, tensor_args):
        in_refs = []
        in_avals = []
        for a in tensor_args:
            if isinstance(a, Tensor) and hasattr(a, "_static_var_id"):
                in_refs.append(("var", a._static_var_id))
                in_avals.append(self.var_avals[a._static_var_id])
            elif isinstance(a, Tensor):
                self.captured[id(a)] = a
                in_refs.append(("const", id(a)))
                in_avals.append(jax.ShapeDtypeStruct(
                    tuple(a._value.shape), a._value.dtype))
            else:
                arr = jnp.asarray(a) if not isinstance(a, jax.Array) else a
                holder = Tensor(arr)
                self.captured[id(holder)] = holder
                in_refs.append(("const", id(holder)))
                in_avals.append(jax.ShapeDtypeStruct(
                    tuple(arr.shape), arr.dtype))
        out_aval = jax.eval_shape(impl, *in_avals)  # ≙ InferMeta
        outs = out_aval if isinstance(out_aval, tuple) else (out_aval,)
        out_ids = []
        out_tensors = []
        for av in outs:
            vid = next(_var_ids)
            t = _symbolic_tensor(av)
            t._static_var_id = vid
            self.var_avals[vid] = av
            self.var_names[vid] = t.name
            out_ids.append(vid)
            out_tensors.append(t)
        self.ops.append(_OpRecord(op_name, impl, in_refs, len(outs), out_ids))
        self.version += 1
        return (tuple(out_tensors) if isinstance(out_aval, tuple)
                else out_tensors[0])

    # -- backward / optimize --
    def append_backward(self, loss: Tensor, parameter_list=None):
        if not hasattr(loss, "_static_var_id"):
            raise ValueError("append_backward: loss is not a Variable of "
                             "this program")
        self._loss_var = loss._static_var_id
        params = [p for p in (parameter_list or
                              [t for t in self.captured.values()
                               if t._is_param])
                  if not p.stop_gradient]
        grads = []
        for p in params:
            gvid = next(_var_ids)
            gt = _symbolic_tensor(jax.ShapeDtypeStruct(
                tuple(p._value.shape), p._value.dtype), p.name + "@GRAD")
            gt._static_var_id = gvid
            self.var_avals[gvid] = gt._value
            self.var_names[gvid] = gt.name
            self.grad_of[gvid] = p
            grads.append((p, gt))
        self.version += 1
        return grads

    def global_block(self):
        return self

    def all_parameters(self):
        return [t for t in self.captured.values() if t._is_param]

    def __str__(self):
        lines = [f"Program(ops={len(self.ops)}, feeds={list(self.feeds)})"]
        for op in self.ops:
            ins = ", ".join(
                self.var_names.get(k, "?") if kind == "var" else "const"
                for kind, k in op.inputs)
            outs = ", ".join(self.var_names[i] for i in op.out_ids)
            lines.append(f"  {outs} = {op.op_name}({ins})")
        return "\n".join(lines)


_default_main_program = Program()
_default_startup_program = Program()  # params init eagerly; kept for parity
_building: List[Program] = []


def default_main_program() -> Program:
    return _default_main_program


def default_startup_program() -> Program:
    return _default_startup_program


def current_build_program() -> Optional[Program]:
    return _building[-1] if _building else None


class program_guard:
    """Route op capture into ``main_program`` (≙ base/framework.py
    program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main_program = main_program
        self.startup_program = startup_program

    def __enter__(self):
        _building.append(self.main_program)
        _dispatch_mod.set_static_builder(_record_into_current)
        return self

    def __exit__(self, *exc):
        _building.pop()
        if not _building:
            _dispatch_mod.set_static_builder(None)
        return False


def _record_into_current(op_name, impl, tensor_args):
    return _building[-1].record(op_name, impl, tensor_args)


def data(name: str, shape: Sequence[int], dtype="float32", lod_level=0):
    """Feed placeholder (≙ paddle.static.data)."""
    prog = current_build_program()
    if prog is None:
        raise RuntimeError("static.data() must be called under program_guard")
    shape = [1 if (s is None or s < 0) else s for s in shape]
    return prog.add_feed(name, shape, dtype)


def append_backward(loss, parameter_list=None):
    prog = current_build_program() or default_main_program()
    return prog.append_backward(loss, parameter_list)


class Executor:
    """Compiles and runs Programs (≙ base/executor.py:1036 over
    StandaloneExecutor). The compile cache is keyed by (program identity,
    program version, fetch ids) — the analogue of _ExecutorCache."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list=None, return_numpy: bool = True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_ids = []
        for f in fetch_list:
            if not hasattr(f, "_static_var_id"):
                raise ValueError(f"fetch target {f!r} is not a Variable of "
                                 "the program")
            fetch_ids.append(f._static_var_id)

        key = (id(program), program.version, tuple(fetch_ids))
        entry = self._cache.get(key)
        if entry is None:
            entry = self._compile(program, fetch_ids)
            self._cache[key] = entry
        fn, param_keys, needs_grads = entry

        feed_vals = []
        for name in sorted(program.feeds):
            if name not in feed:
                raise ValueError(f"missing feed {name!r}")
            v = feed[name]
            v = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            feed_vals.append(v)
        param_vals = [program.captured[k]._value for k in param_keys]

        outs, new_params = fn(param_vals, feed_vals)
        if new_params is not None:  # optimizer updates: write back to scope
            for k, new in zip(param_keys, new_params):
                program.captured[k]._value = new
        results = [np.asarray(o) if return_numpy else Tensor(o) for o in outs]
        return results

    def _compile(self, program: Program, fetch_ids):
        param_keys = sorted(program.captured)
        key_pos = {k: i for i, k in enumerate(param_keys)}
        grad_fetches = [fid for fid in fetch_ids if fid in program.grad_of]
        needs_grads = bool(grad_fetches) or bool(program.updates)

        def replay(param_vals, feed_vals):
            env = {}
            for i, name in enumerate(sorted(program.feeds)):
                env[program.feeds[name]] = feed_vals[i]

            def read(ref):
                kind, k = ref
                return env[k] if kind == "var" else param_vals[key_pos[k]]

            for op in program.ops:
                ins = [read(r) for r in op.inputs]
                out = op.impl(*ins)
                outs = out if isinstance(out, tuple) else (out,)
                for vid, o in zip(op.out_ids, outs):
                    env[vid] = o
            return env

        # parameters whose grads are demanded (fetch or updates)
        grad_params = [program.grad_of[fid] for fid in grad_fetches]
        upd_params = [p for (p, _) in program.updates]
        diff_tensors = {id(p): p for p in grad_params + upd_params}
        diff_keys = list(diff_tensors)

        def fn(param_vals, feed_vals):
            if needs_grads:
                def loss_of(diff_vals):
                    pv = list(param_vals)
                    for k, v in zip(diff_keys, diff_vals):
                        pv[key_pos[k]] = v
                    env = replay(pv, feed_vals)
                    return env[program._loss_var]

                diff_vals = [param_vals[key_pos[k]] for k in diff_keys]
                loss, grads = jax.value_and_grad(loss_of)(diff_vals)
                grad_by_key = dict(zip(diff_keys, grads))
                env = replay(param_vals, feed_vals)
                outs = []
                for fid in fetch_ids:
                    if fid in program.grad_of:
                        outs.append(grad_by_key[id(program.grad_of[fid])])
                    else:
                        outs.append(env[fid])
                new_params = None
                if program.updates:
                    new_params = list(param_vals)
                    for p, update_fn in program.updates:
                        i = key_pos[id(p)]
                        new_params[i] = update_fn(param_vals[i],
                                                  grad_by_key[id(p)])
                return outs, new_params
            env = replay(param_vals, feed_vals)
            return [env[fid] for fid in fetch_ids], None

        jfn = jax.jit(fn)
        return jfn, param_keys, needs_grads


_global_scope = {}


def global_scope():
    return _global_scope
