"""paddle_tpu.static.nn — static-graph layer builders (≙ paddle.static.nn).

Each builder constructs the underlying nn layer eagerly (its parameters are
concrete, registered as captured vars of the current Program — the
startup-program role) and applies it to the symbolic input, recording the
compute into the Program.
"""

from __future__ import annotations

from ..nn.layer.common import Linear, Embedding
from ..nn.layer.conv import Conv2D
from ..nn.layer.norm import BatchNorm2D
from ..nn import functional as F
from .control_flow import (cond, while_loop, case,  # noqa: F401
                           switch_case)

__all__ = ["fc", "embedding", "conv2d", "batch_norm",
           "cond", "while_loop", "case", "switch_case"]


def fc(x, size, num_flatten_dims=1, activation=None, name=None):
    layer = Linear(x.shape[-1], size)
    out = layer(x)
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, name=None):
    layer = Embedding(size[0], size[1], padding_idx=padding_idx)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           activation=None, name=None):
    layer = Conv2D(input.shape[1], num_filters, filter_size, stride=stride,
                   padding=padding)
    out = layer(input)
    if activation is not None:
        out = getattr(F, activation)(out)
    return out


def batch_norm(input, name=None):
    layer = BatchNorm2D(input.shape[1])
    return layer(input)
