"""paddle_tpu.static — static-graph API.

TPU-native static mode (SURVEY §7: ProgramDesc/PIR ≙ captured DAG compiled
as one XLA program). Two complementary surfaces:

- Program capture: ``data`` + ``program_guard`` + ``Executor`` +
  ``append_backward`` (see program.py) — the reference's
  build-program-then-run workflow, compiled whole-program by XLA.
- jit bridge: ``InputSpec`` and ``save/load_inference_model`` over
  paddle_tpu.jit traced artifacts (the deployment path).
"""

from ..jit.api import InputSpec
from ..jit import save as _jit_save, load as _jit_load
from .program import (  # noqa: F401
    Program, program_guard, data, Executor, append_backward,
    default_main_program, default_startup_program, global_scope,
)
from . import nn  # noqa: F401
from . import quantization  # noqa: F401
from .control_flow import cond, while_loop, case, switch_case  # noqa: F401

__all__ = [
    "InputSpec", "save_inference_model", "load_inference_model",
    "Program", "program_guard", "data", "Executor", "append_backward",
    "default_main_program", "default_startup_program", "global_scope", "nn",
    "cond", "while_loop", "case", "switch_case",
]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    program = kwargs.get("program")
    if program is None:
        raise ValueError(
            "save_inference_model requires program=<Layer or callable>; "
            "in this framework an inference program is a traced callable")
    specs = [InputSpec(v.shape, v.dtype) for v in feed_vars]
    _jit_save(program, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)
