"""paddle_tpu.static — static-graph API shims.

On this framework "static mode" IS jit tracing (SURVEY §7: ProgramDesc/PIR ≙
jaxpr/StableHLO).  The paddle.static surface maps accordingly: InputSpec is
shared with paddle_tpu.jit; save/load_inference_model serialize exported
StableHLO programs.
"""

from ..jit.api import InputSpec
from ..jit import save as _jit_save, load as _jit_load

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    program = kwargs.get("program")
    if program is None:
        raise ValueError(
            "save_inference_model requires program=<Layer or callable>; "
            "in this framework an inference program is a traced callable")
    specs = [InputSpec(v.shape, v.dtype) for v in feed_vars]
    _jit_save(program, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    return _jit_load(path_prefix)
