"""Static-graph quantization namespace (reference:
``python/paddle/static/quantization``: PTQ/QAT for static programs).

In this framework the static path is traced-and-compiled from the same
layers, so static quantization IS the quantization package applied before
tracing: quantize/convert the model with ``paddle.quantization`` and then
``paddle.jit.save`` / ``Program`` capture the QDQ (or int8) graph.  The
reference class names are provided as thin aliases so ported code finds
them."""

from __future__ import annotations

from ..quantization import PTQ, QAT, QuantConfig
from ..quantization.observers import (AbsmaxObserver,
                                      MovingAverageAbsmaxObserver,
                                      PerChannelAbsmaxObserver)

__all__ = ["PTQ", "QAT", "QuantConfig", "quant_post_static",
           "AbsmaxObserver", "MovingAverageAbsmaxObserver",
           "PerChannelAbsmaxObserver"]


def quant_post_static(model, calibration_loader, batch_nums=10,
                      activation_observer=None, weight_bits=8):
    """Post-training static quantization driver (reference
    quant_post_static): calibrate on ``batch_nums`` batches and return
    the converted int8 model."""
    from ..quantization.config import quanter_factory

    obs = activation_observer or AbsmaxObserver
    ptq = PTQ(QuantConfig(
        activation=obs,
        weight=quanter_factory(PerChannelAbsmaxObserver,
                               bit_length=weight_bits)))
    # the caller's fp32 model stays untouched (reference semantics)
    qmodel = ptq.quantize(model, inplace=False)
    for i, batch in enumerate(calibration_loader):
        if i >= batch_nums:
            break
        x = batch[0] if isinstance(batch, (tuple, list)) else batch
        qmodel(x)
    return ptq.convert(qmodel)
