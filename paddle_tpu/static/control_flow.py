"""Control-flow ops: cond / while_loop / case / switch_case.

Capability analogue of ``paddle.static.nn.{cond,while_loop,case,
switch_case}`` (reference: python/paddle/static/nn/control_flow.py over
the conditional_block/while C++ ops) — and of the dy2static AST
transforms whose whole purpose is to rewrite Python ``if``/``while`` into
these ops.  The TPU-native design: in eager mode the predicate is
concrete, so the chosen branch simply runs (reference dygraph semantics);
under a jit trace the predicate is a tracer and the op lowers to
``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` — XLA's structured
control flow, which is what the reference's AST transpiler ultimately
emulates.  Outputs keep their eager types: leaves that the branch
returned as Tensors come back as Tensors, raw arrays stay raw.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _is_tensor(x):
    return isinstance(x, Tensor)


def _pred_value(pred):
    return pred._value if isinstance(pred, Tensor) else pred


class _StructMeta:
    """Records the pytree structure + which leaves were Tensors, so the
    traced path can reconstruct exactly what the eager path returns.
    Every branch must agree on both — mismatches raise instead of being
    silently coerced to the first branch's typing."""

    def __init__(self):
        self.treedef = None
        self.is_tensor = None
        self.out_is_tensor = None  # body's typing (while-loop outputs)

    def flatten(self, out, coerce_flags=False):
        """coerce_flags: accept Tensor/raw typing differences in the loop
        carry (the body may box raw init vars into Tensors); the body's
        typing is remembered so the final outputs match what the eager
        loop would return.  Structure differences always raise."""
        from ..core.pytree import flatten_tensors
        raw, treedef, flags = flatten_tensors(out)
        if self.treedef is None:
            self.treedef = treedef
            self.is_tensor = flags
        elif treedef != self.treedef:
            raise ValueError(
                "control flow: branches must return the same pytree "
                f"structure (got {treedef} vs {self.treedef})")
        elif flags != self.is_tensor and not coerce_flags:
            raise ValueError(
                "control flow: branches must agree on which leaves are "
                f"Tensors vs raw arrays (got {flags} vs {self.is_tensor})")
        if coerce_flags:
            self.out_is_tensor = flags
        return raw

    def unflatten(self, leaves, final=False):
        from ..core.pytree import unflatten_tensors
        flags = (self.out_is_tensor
                 if final and self.out_is_tensor is not None
                 else self.is_tensor)
        return unflatten_tensors(leaves, self.treedef, flags)


def cond(pred, true_fn, false_fn, name=None):
    """Run ``true_fn()`` if pred else ``false_fn()``.  Both branches must
    return structures with matching shapes/dtypes when traced."""
    pv = _pred_value(pred)
    if not _is_tracer(pv):
        return true_fn() if bool(pv) else false_fn()
    meta = _StructMeta()
    out = lax.cond(jnp.asarray(pv).astype(bool).reshape(()),
                   lambda _: meta.flatten(true_fn()),
                   lambda _: meta.flatten(false_fn()),
                   0)
    return meta.unflatten(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """While loop over a tuple/list of loop vars.

    cond_fn(*vars) -> bool scalar; body_fn(*vars) -> same-structured vars.
    Tracedness is decided from the loop vars (a cond_fn that closes over a
    traced value while all loop vars are concrete is not supported).
    """
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise TypeError("while_loop: loop_vars must be a non-empty "
                        "list/tuple")
    meta = _StructMeta()
    init = meta.flatten(tuple(loop_vars))
    traced = any(_is_tracer(l) for l in init)
    if not traced:
        vars_ = tuple(loop_vars)
        while bool(_pred_value(cond_fn(*vars_))):
            out = body_fn(*vars_)
            vars_ = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        return list(vars_)

    def c(carry):
        pv = _pred_value(cond_fn(*meta.unflatten(carry)))
        return jnp.asarray(pv).astype(bool).reshape(())

    def b(carry):
        out = body_fn(*meta.unflatten(carry))
        out = tuple(out) if isinstance(out, (list, tuple)) else (out,)
        return meta.flatten(out, coerce_flags=True)

    final = lax.while_loop(c, b, init)
    # outputs carry the body's typing (what the eager loop returns)
    return list(meta.unflatten(final, final=True))


def case(pred_fn_pairs, default=None, name=None):
    """First pair whose pred is true wins (reference static.nn.case; when
    ``default`` is None the last pair's fn doubles as the default)."""
    if not pred_fn_pairs:
        raise TypeError("case: pred_fn_pairs must be non-empty")
    pairs = list(pred_fn_pairs)
    if default is None:
        # reference semantics: the final fn is the fallback — drop its
        # predicate so it is not traced twice (once as branch, once as tail)
        default = pairs[-1][1]
        pairs = pairs[:-1]
        if not pairs:
            return default()
    preds = [_pred_value(p) for p, _ in pairs]
    if not any(_is_tracer(p) for p in preds):
        for p, fn in pairs:
            if bool(_pred_value(p)):
                return fn()
        return default()
    fns = [fn for _, fn in pairs]

    def build(i):
        if i == len(fns):
            return default
        return lambda: cond(Tensor(jnp.asarray(preds[i])), fns[i],
                            build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Dispatch on an integer index (reference static.nn.switch_case).
    branch_fns: dict {index: fn} or list of (index, fn) or list of fns."""
    if not branch_fns:
        raise TypeError("switch_case: branch_fns must be non-empty")
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items(), key=lambda kv: kv[0])
    elif branch_fns and isinstance(branch_fns[0], (list, tuple)):
        items = sorted(((i, f) for i, f in branch_fns),
                       key=lambda kv: kv[0])
    else:
        items = list(enumerate(branch_fns))
    seen = set()
    for k, _ in items:
        if k in seen:
            raise ValueError(
                f"switch_case: duplicate branch index {k}")
        seen.add(k)
    idx_v = _pred_value(branch_index)
    if not _is_tracer(idx_v):
        i = int(idx_v)
        for key, fn in items:
            if key == i:
                return fn()
        if default is not None:
            return default()
        return items[-1][1]()
    keys = jnp.asarray([k for k, _ in items])
    fns = [f for _, f in items]
    if default is not None:
        fns = fns + [default]
    # unmatched index selects the final entry (the default when given,
    # else the last branch — reference behavior)
    matches = keys == jnp.asarray(idx_v).reshape(())
    sel = jnp.where(jnp.any(matches), jnp.argmax(matches), len(fns) - 1)
    meta = _StructMeta()
    out = lax.switch(sel, [lambda f=f: meta.flatten(f()) for f in fns])
    return meta.unflatten(out)
