"""Weight-decay regularizers (analogue of ``python/paddle/regularizer.py``).

The reference appends regularization ops to the gradient before the optimizer
update (L2Decay: ``grad += coeff * param``; L1Decay: ``grad += coeff *
sign(param)``).  Here the optimizer consumes these objects directly in its
fused update — XLA folds the extra elementwise term into the update kernel.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base class.  ``__call__(grad, param) -> regularized grad``."""

    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)
        self._coeff = float(coeff)  # alias the optimizer reads

    def __call__(self, grad, param):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: ``grad + coeff * sign(param)``."""

    _is_l1 = True

    def __call__(self, grad, param):
        if not self.coeff:
            return grad
        return grad + self.coeff * jnp.sign(param).astype(grad.dtype)


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: ``grad + coeff * param``."""

    _is_l1 = False

    def __call__(self, grad, param):
        if not self.coeff:
            return grad
        return grad + self.coeff * param.astype(grad.dtype)
