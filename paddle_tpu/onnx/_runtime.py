"""Tiny numpy evaluator for the exported ONNX subset.

Runs the graphs ``_export.py`` emits — the round-trip check that the
artifact is semantically correct without onnxruntime (absent from this
environment).  Parses the wire format with ``_proto.decode``.
"""

from __future__ import annotations

import numpy as np

from . import _proto as P

_NP_DTYPES = {1: np.float32, 11: np.float64, 7: np.int64, 6: np.int32,
              9: np.bool_, 10: np.float16}


def _parse_tensor(buf: bytes) -> tuple:
    msg = P.decode(buf)
    dims = [int(d) for d in msg.get(1, [])]
    dtype = _NP_DTYPES[int(msg[2][0])]
    name = msg[8][0].decode()
    arr = np.frombuffer(msg[9][0], dtype=dtype).reshape(dims)
    return name, arr


def _parse_attrs(node_msg) -> dict:
    attrs = {}
    for a in node_msg.get(5, []):
        am = P.decode(a)
        name = am[1][0].decode()
        atype = int(am[20][0])
        if atype == 2:
            attrs[name] = int(am[3][0])
        elif atype == 7:
            attrs[name] = [int(v) for v in am.get(8, [])]
        else:
            raise NotImplementedError(f"attr type {atype}")
    return attrs


def load_model(path: str) -> dict:
    """-> {nodes: [(op, ins, outs, attrs)], initializers: {name: arr},
    inputs: [name], outputs: [name], opset: int}"""
    with open(path, "rb") as f:
        model = P.decode(f.read())
    graph = P.decode(model[7][0])
    nodes = []
    for n in graph.get(1, []):
        nm = P.decode(n)
        nodes.append((
            nm[4][0].decode(),
            [s.decode() for s in nm.get(1, [])],
            [s.decode() for s in nm.get(2, [])],
            _parse_attrs(nm),
        ))
    inits = dict(_parse_tensor(t) for t in graph.get(5, []))
    ins = [P.decode(vi)[1][0].decode() for vi in graph.get(11, [])]
    outs = [P.decode(vi)[1][0].decode() for vi in graph.get(12, [])]
    opset = int(P.decode(model[8][0])[2][0])
    return {"nodes": nodes, "initializers": inits, "inputs": ins,
            "outputs": outs, "opset": opset,
            "ir_version": int(model[1][0])}


def _conv2d(x, w, b, attrs):
    pads = attrs.get("pads", [0, 0, 0, 0])
    sh, sw = attrs.get("strides", [1, 1])
    dh, dw = attrs.get("dilations", [1, 1])
    groups = attrs.get("group", 1)
    n, cin, h, wd = x.shape
    cout, cin_g, kh, kw = w.shape
    x = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    eh = (kh - 1) * dh + 1
    ew = (kw - 1) * dw + 1
    oh = (x.shape[2] - eh) // sh + 1
    ow = (x.shape[3] - ew) // sw + 1
    out = np.zeros((n, cout, oh, ow), np.float32)
    og = cout // groups
    for gidx in range(groups):
        xs = x[:, gidx * cin_g:(gidx + 1) * cin_g]
        ws = w[gidx * og:(gidx + 1) * og]
        for i in range(oh):
            for j in range(ow):
                patch = xs[:, :, i * sh:i * sh + eh:dh,
                           j * sw:j * sw + ew:dw]
                out[:, gidx * og:(gidx + 1) * og, i, j] = np.einsum(
                    "nchw,ochw->no", patch, ws)
    if b is not None:
        out += b[None, :, None, None]
    return out


def _maxpool(x, attrs):
    kh, kw = attrs["kernel_shape"]
    sh, sw = attrs.get("strides", [kh, kw])
    pads = attrs.get("pads", [0, 0, 0, 0])
    x = np.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])),
               constant_values=-np.inf)
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    out = np.full((n, c, oh, ow), -np.inf, np.float32)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * sh:i * sh + kh,
                                j * sw:j * sw + kw].max(axis=(2, 3))
    return out


def run_model(path: str, *inputs) -> list:
    m = load_model(path)
    env = dict(m["initializers"])
    for nm, arr in zip(m["inputs"], inputs):
        env[nm] = np.asarray(arr)

    simple = {
        "Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
        "Div": np.divide, "Max": np.maximum, "Min": np.minimum,
        "Neg": np.negative, "Exp": np.exp, "Log": np.log,
        "Tanh": np.tanh, "Sqrt": np.sqrt, "Abs": np.abs,
        "Greater": np.greater, "Less": np.less, "Equal": np.equal,
        "Pow": np.power, "Identity": lambda x: x,
        "Sigmoid": lambda x: 1.0 / (1.0 + np.exp(-x)),
        "Floor": np.floor, "Sign": np.sign,
        "Sin": np.sin, "Cos": np.cos,
        "GreaterOrEqual": np.greater_equal, "LessOrEqual": np.less_equal,
        "And": np.logical_and, "Or": np.logical_or,
        "Not": np.logical_not,
    }
    try:
        from math import erf as _erf
        simple["Erf"] = np.vectorize(_erf, otypes=[np.float32])
    except ImportError:
        pass

    for op, ins, outs, attrs in m["nodes"]:
        a = [env[i] for i in ins]
        if op in simple:
            r = simple[op](*a)
        elif op == "MatMul":
            r = a[0] @ a[1]
        elif op == "Conv":
            r = _conv2d(a[0], a[1], a[2] if len(a) > 2 else None, attrs)
        elif op == "MaxPool":
            r = _maxpool(a[0], attrs)
        elif op == "Reshape":
            r = a[0].reshape([int(d) for d in a[1]])
        elif op == "Transpose":
            r = np.transpose(a[0], attrs["perm"])
        elif op == "Expand":
            r = np.broadcast_to(a[0], [int(d) for d in a[1]])
        elif op == "Where":
            r = np.where(a[0], a[1], a[2])
        elif op == "Cast":
            r = a[0].astype(_NP_DTYPES[attrs["to"]])
        elif op in ("ReduceSum", "ReduceMax", "ReduceMin"):
            fn = {"ReduceSum": np.sum, "ReduceMax": np.max,
                  "ReduceMin": np.min}[op]
            # opset-13 ReduceSum carries axes as input; Max/Min as attr
            axes = (tuple(int(d) for d in a[1]) if len(a) > 1
                    else tuple(attrs["axes"]))
            r = fn(a[0], axis=axes,
                   keepdims=bool(attrs.get("keepdims", 0)))
        elif op == "Concat":
            r = np.concatenate(a, axis=attrs["axis"])
        elif op == "Squeeze":
            r = np.squeeze(a[0], axis=tuple(int(d) for d in a[1]))
        elif op == "Gather":
            r = np.take(a[0], a[1].astype(np.int64),
                        axis=attrs.get("axis", 0))
        elif op == "Slice":
            starts, ends, axes, steps = (
                [int(v) for v in a[1]], [int(v) for v in a[2]],
                [int(v) for v in a[3]] if len(a) > 3
                else list(range(len(a[1]))),
                [int(v) for v in a[4]] if len(a) > 4 else [1] * len(a[1]))
            sl = [slice(None)] * a[0].ndim
            for s, e, ax, st in zip(starts, ends, axes, steps):
                sl[ax] = slice(s, e, st)
            r = a[0][tuple(sl)]
        elif op == "Split":
            sizes = [int(v) for v in a[1]]
            pieces = np.split(a[0], np.cumsum(sizes)[:-1],
                              axis=attrs["axis"])
            for o, piece in zip(outs, pieces):
                env[o] = piece
            continue
        elif op == "ArgMax":
            r = np.argmax(a[0], axis=attrs["axis"]).astype(np.int64)
            if attrs.get("keepdims", 1):
                r = np.expand_dims(r, attrs["axis"])
        elif op == "CumSum":
            r = np.cumsum(a[0], axis=int(a[1]))
        else:
            raise NotImplementedError(f"onnx runtime: op {op}")
        env[outs[0]] = r
    return [env[o] for o in m["outputs"]]
