"""paddle_tpu.onnx (analogue of ``python/paddle/onnx/export.py``, which
bridges to the external paddle2onnx package).

This build is air-gapped and the ``onnx`` package is not installed, so
``export`` is gated: it raises with a clear message pointing at the
native serialization path — ``paddle.jit.save`` (StableHLO), the
TPU-world deployment artifact.  (Graph emission would slot in here once
an onnx runtime is available; nothing is traced before the gate.)
"""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path: str, input_spec=None, opset_version: int = 11,
           **configs):
    try:
        import onnx  # noqa: F401
    except ImportError:
        raise RuntimeError(
            "paddle_tpu.onnx.export requires the 'onnx' package, which is "
            "not available in this environment. Use paddle.jit.save for "
            "the native (StableHLO) deployment artifact, or install onnx "
            "to enable ONNX export.")
    raise NotImplementedError(
        "ONNX graph emission is not implemented in this build; use "
        "paddle.jit.save (StableHLO) for deployment.")
