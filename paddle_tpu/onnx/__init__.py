"""paddle_tpu.onnx (analogue of ``python/paddle/onnx/export.py:22``,
which bridges to the external paddle2onnx package).

This build is air-gapped (no ``onnx`` package), so the exporter writes
the ONNX protobuf wire format directly: the layer's forward traces to a
jaxpr and each primitive maps to an ONNX-13 op (``_export.py``), with
weights as initializers.  The supported primitive subset covers the
Linear/Conv/pool/activation model families; unsupported primitives
raise with the primitive named.  ``paddle_tpu.onnx.runtime.run_model``
is a numpy evaluator for the emitted subset — the environment's
round-trip check (no onnxruntime here).
"""

from __future__ import annotations

from ._export import export  # noqa: F401
from . import _runtime as runtime  # noqa: F401

__all__ = ["export", "runtime"]
