"""paddle.onnx.export — jaxpr -> ONNX graph emission.

Reference: ``python/paddle/onnx/export.py:22`` (which shells out to
paddle2onnx).  TPU-native approach: the layer's forward is traced to a
jaxpr (the framework IR) and each primitive maps to an ONNX op; weights
become initializers.  The wire format is written directly (_proto.py) —
no onnx package needed.  Supported primitive subset covers the
Linear/Conv/pool/activation model families (LeNet-class and MLP-class
exports); anything outside raises with the offending primitive named.
"""

from __future__ import annotations

import numpy as np

from . import _proto as P

# ONNX TensorProto.DataType
_DTYPES = {"float32": 1, "float64": 11, "int64": 7, "int32": 6,
           "bool": 9, "float16": 10}

_OPSET = 13


def _np_dtype_code(dt) -> int:
    name = np.dtype(dt).name
    if name not in _DTYPES:
        raise NotImplementedError(f"onnx export: dtype {name} unsupported")
    return _DTYPES[name]


def _tensor_proto(name: str, arr: np.ndarray) -> bytes:
    out = b""
    for d in arr.shape:
        out += P.f_int(1, d)
    out += P.f_int(2, _np_dtype_code(arr.dtype))
    out += P.f_bytes(8, name)
    out += P.f_bytes(9, np.ascontiguousarray(arr).tobytes())
    return out


def _value_info(name: str, shape, dtype) -> bytes:
    dims = b"".join(P.f_msg(1, P.f_int(1, d)) for d in shape)
    ttype = P.f_int(1, _np_dtype_code(dtype)) + P.f_msg(2, dims)
    return P.f_bytes(1, name) + P.f_msg(2, P.f_msg(1, ttype))


def _attr_int(name, v):
    return P.f_bytes(1, name) + P.f_int(3, v) + P.f_int(20, 2)


def _attr_ints(name, vs):
    return (P.f_bytes(1, name) +
            b"".join(P.f_int(8, v) for v in vs) + P.f_int(20, 7))


def _node(op_type, inputs, outputs, attrs=()):
    out = b"".join(P.f_bytes(1, i) for i in inputs)
    out += b"".join(P.f_bytes(2, o) for o in outputs)
    out += P.f_bytes(4, op_type)
    out += b"".join(P.f_msg(5, a) for a in attrs)
    return out


class _Graph:
    def __init__(self):
        self.nodes = []
        self.initializers = []
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(_tensor_proto(name, np.asarray(arr)))
        return name

    def add(self, op, inputs, attrs=(), n_out=1, hint=None):
        outs = [self.fresh(hint or op.lower()) for _ in range(n_out)]
        self.nodes.append(_node(op, inputs, outs, attrs))
        return outs[0] if n_out == 1 else outs


_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "neg": "Neg", "exp": "Exp",
    "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "abs": "Abs", "erf": "Erf", "floor": "Floor",
    "sign": "Sign", "gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
    "le": "LessOrEqual", "eq": "Equal", "pow": "Pow", "and": "And",
    "or": "Or", "not": "Not", "sin": "Sin", "cos": "Cos",
}


def _emit_eqn(g: _Graph, eqn, names):
    prim = eqn.primitive.name
    ins = [names[v] if not hasattr(v, "val") else g.const(np.asarray(v.val))
           for v in eqn.invars]

    def out1(name):
        names[eqn.outvars[0]] = name

    if prim in _ELEMENTWISE:
        out1(g.add(_ELEMENTWISE[prim], ins))
    elif prim == "name":
        # checkpoint_name remat annotation — identity at inference
        out1(g.add("Identity", ins))
    elif prim in ("jit", "pjit", "custom_jvp_call", "custom_vjp_call",
                  "custom_jvp_call_jaxpr", "closed_call", "remat",
                  "checkpoint"):
        sub = (eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
               or eqn.params.get("fun_jaxpr"))
        if sub is None:
            raise NotImplementedError(
                f"onnx export: opaque call primitive {prim!r}")
        sub_jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
        consts = list(getattr(sub, "consts", ()))
        n_args = len(sub_jaxpr.invars) - len(consts)
        # custom_jvp_call passes (fn-args...) matching the tail invars
        inner_names = {}
        for cv, c in zip(sub_jaxpr.invars[:len(consts)], consts):
            inner_names[cv] = g.const(np.asarray(c))
        for iv, nm in zip(sub_jaxpr.invars[len(consts):], ins[-n_args:]):
            inner_names[iv] = nm
        _emit_jaxpr(g, sub_jaxpr, inner_names)
        for ov, iv in zip(eqn.outvars, sub_jaxpr.outvars):
            names[ov] = inner_names[iv]
    elif prim == "dot_general":
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = eqn.invars[:2]
        ls, rs = lhs.aval.shape, rhs.aval.shape
        if not lb and not rb and lc == (lhs.aval.ndim - 1,) and rc == (0,):
            # plain [.., M, K] @ [K, N] — numpy-matmul semantics directly
            out1(g.add("MatMul", ins))
        else:
            # general case (batched attention matmuls): canonicalize to
            # [B.., prod(lfree), prod(contract)] @ [B.., prod(contract),
            # prod(rfree)] — ONNX MatMul is numpy matmul, so stacked
            # batch dims multiply pairwise; output reshapes to the
            # jax dot_general layout (batch, lhs free, rhs free)
            lfree = [d for d in range(len(ls))
                     if d not in lc and d not in lb]
            rfree = [d for d in range(len(rs))
                     if d not in rc and d not in rb]
            bshape = [ls[d] for d in lb]

            def prod(dims, shape):
                n = 1
                for d in dims:
                    n *= shape[d]
                return n

            lt = g.add("Transpose", [ins[0]],
                       [_attr_ints("perm", list(lb) + lfree + list(lc))])
            lr = g.add("Reshape", [lt, g.const(np.asarray(
                bshape + [prod(lfree, ls), prod(lc, ls)], np.int64),
                "shape")])
            rt = g.add("Transpose", [ins[1]],
                       [_attr_ints("perm", list(rb) + list(rc) + rfree)])
            rr = g.add("Reshape", [rt, g.const(np.asarray(
                bshape + [prod(rc, rs), prod(rfree, rs)], np.int64),
                "shape")])
            mm = g.add("MatMul", [lr, rr])
            out1(g.add("Reshape", [mm, g.const(np.asarray(
                eqn.outvars[0].aval.shape, np.int64), "shape")]))
    elif prim == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        if tuple(dn.lhs_spec[:2]) != (0, 1) or \
                tuple(dn.rhs_spec[:2]) != (0, 1):
            raise NotImplementedError(
                "onnx export: conv must be NCHW/OIHW layout")
        pads_lo_hi = eqn.params["padding"]
        pads = [p[0] for p in pads_lo_hi] + [p[1] for p in pads_lo_hi]
        attrs = [
            _attr_ints("strides", eqn.params["window_strides"]),
            _attr_ints("pads", pads),
            _attr_ints("dilations", eqn.params["rhs_dilation"]),
            _attr_int("group", eqn.params["feature_group_count"]),
        ]
        out1(g.add("Conv", ins, attrs))
    elif prim == "reduce_window_max":
        wd = eqn.params["window_dimensions"]
        ws = eqn.params["window_strides"]
        pad = eqn.params["padding"]
        if wd[0] != 1 or wd[1] != 1:
            raise NotImplementedError(
                "onnx export: reduce_window_max must pool spatial dims "
                "only (NCHW)")
        attrs = [
            _attr_ints("kernel_shape", wd[2:]),
            _attr_ints("strides", ws[2:]),
            _attr_ints("pads", [p[0] for p in pad[2:]] +
                       [p[1] for p in pad[2:]]),
        ]
        out1(g.add("MaxPool", ins[:1], attrs))
    elif prim == "add_any":
        out1(g.add("Add", ins))
    elif prim == "reshape":
        shape = g.const(np.asarray(eqn.params["new_sizes"], np.int64),
                        "shape")
        out1(g.add("Reshape", [ins[0], shape]))
    elif prim == "transpose":
        out1(g.add("Transpose", ins,
                   [_attr_ints("perm", eqn.params["permutation"])]))
    elif prim == "broadcast_in_dim":
        shape = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        src_shape = eqn.invars[0].aval.shape
        # reshape into rank-matched form (1s elsewhere), then Expand
        mid = [1] * len(shape)
        for i, d in enumerate(bdims):
            mid[d] = src_shape[i]
        rname = g.add("Reshape", [
            ins[0], g.const(np.asarray(mid, np.int64), "shape")])
        out1(g.add("Expand", [
            rname, g.const(np.asarray(shape, np.int64), "shape")]))
    elif prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError("onnx export: select_n arity != 3")
        # jax select_n(pred, false_val, true_val) vs Where(cond, X, Y)
        # (X where cond true)
        out1(g.add("Where", [ins[0], ins[2], ins[1]]))
    elif prim == "convert_element_type":
        out1(g.add("Cast", ins,
                   [_attr_int("to", _np_dtype_code(
                       eqn.params["new_dtype"]))]))
    elif prim == "reduce_sum":
        # opset 13: ReduceSum takes axes as an INPUT (ReduceMax/Min
        # still use the attribute until opset 18)
        axes = g.const(np.asarray(eqn.params["axes"], np.int64), "axes")
        out1(g.add("ReduceSum", [ins[0], axes],
                   [_attr_int("keepdims", 0)]))
    elif prim in ("reduce_max", "reduce_min"):
        op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin"}[prim]
        attrs = [_attr_ints("axes", list(eqn.params["axes"])),
                 _attr_int("keepdims", 0)]
        out1(g.add(op, ins[:1], attrs))
    elif prim == "integer_pow":
        y = eqn.params["y"]
        out1(g.add("Pow", [ins[0],
                           g.const(np.asarray(float(y), np.float32))]))
    elif prim == "square":
        out1(g.add("Mul", [ins[0], ins[0]]))
    elif prim == "rsqrt":
        s = g.add("Sqrt", ins)
        one = g.const(np.asarray(1.0, eqn.invars[0].aval.dtype))
        out1(g.add("Div", [one, s]))
    elif prim == "erfc":
        e = g.add("Erf", ins)
        one = g.const(np.asarray(1.0, eqn.invars[0].aval.dtype))
        out1(g.add("Sub", [one, e]))
    elif prim == "erf_inv":
        raise NotImplementedError(
            "onnx export: erf_inv has no ONNX op")
    elif prim in ("stop_gradient", "copy", "copy_p"):
        out1(g.add("Identity", ins))
    elif prim == "squeeze":
        axes = g.const(np.asarray(eqn.params["dimensions"], np.int64))
        out1(g.add("Squeeze", [ins[0], axes]))
    elif prim == "concatenate":
        out1(g.add("Concat", ins,
                   [_attr_int("axis", eqn.params["dimension"])]))
    elif prim == "iota":
        # static shapes make iota a compile-time constant; store only
        # the 1-D arange and Expand at runtime (a [1,S,S] mask iota
        # would otherwise serialize S^2 dense values)
        shape = tuple(eqn.params["shape"])
        dim = eqn.params["dimension"]
        ar = np.arange(shape[dim], dtype=np.dtype(eqn.params["dtype"]))
        view = [1] * len(shape)
        view[dim] = shape[dim]
        base = g.const(ar.reshape(view), "iota")
        if tuple(view) == shape:
            out1(base)
        else:
            out1(g.add("Expand", [
                base, g.const(np.asarray(shape, np.int64), "shape")]))
    elif prim == "slice":
        starts = list(eqn.params["start_indices"])
        ends = list(eqn.params["limit_indices"])
        steps = list(eqn.params["strides"] or [1] * len(starts))
        axes = list(range(len(starts)))
        out1(g.add("Slice", [
            ins[0],
            g.const(np.asarray(starts, np.int64), "starts"),
            g.const(np.asarray(ends, np.int64), "ends"),
            g.const(np.asarray(axes, np.int64), "axes"),
            g.const(np.asarray(steps, np.int64), "steps")]))
    elif prim == "split":
        sizes = list(eqn.params["sizes"])
        axis = eqn.params["axis"]
        outs = g.add("Split",
                     [ins[0], g.const(np.asarray(sizes, np.int64),
                                      "split")],
                     [_attr_int("axis", axis)], n_out=len(sizes))
        outs = outs if isinstance(outs, list) else [outs]
        for ov, nm in zip(eqn.outvars, outs):
            names[ov] = nm
    elif prim == "gather":
        dn = eqn.params["dimension_numbers"]
        operand, indices = eqn.invars[0], eqn.invars[1]
        oshape = operand.aval.shape
        ishape = indices.aval.shape
        slice_sizes = tuple(eqn.params["slice_sizes"])
        smap = tuple(dn.start_index_map)
        collapsed = tuple(dn.collapsed_slice_dims)
        # the take(x, idx, axis=a) pattern: one indexed dim, collapsed,
        # every other dim sliced whole — ONNX Gather(axis=a)
        take_like = (
            len(smap) == 1 and collapsed == smap
            and getattr(dn, "operand_batching_dims", ()) == ()
            and all(slice_sizes[d] == oshape[d]
                    for d in range(len(oshape)) if d != smap[0])
            and slice_sizes[smap[0]] == 1)
        if not take_like:
            raise NotImplementedError(
                "onnx export: general gather unsupported (only the "
                "take-along-axis pattern maps to ONNX Gather); got "
                f"dimension_numbers {dn}")
        axis = smap[0]
        idx_name = ins[1]
        # lax.gather: the LAST dim of start_indices is the index vector
        # (length == len(start_index_map) == 1 here) — drop it
        if not ishape or ishape[-1] != 1:
            raise NotImplementedError(
                "onnx export: gather index-vector dim must be trailing "
                f"size-1, got indices shape {ishape}")
        idx_shape = ishape[:-1]
        idx_name = g.add("Reshape", [
            idx_name, g.const(np.asarray(idx_shape, np.int64),
                              "shape")])
        want = (tuple(oshape[:axis]) + tuple(idx_shape)
                + tuple(oshape[axis + 1:]))
        if want != tuple(eqn.outvars[0].aval.shape):
            raise NotImplementedError(
                "onnx export: gather output layout differs from ONNX "
                f"Gather semantics ({want} vs "
                f"{tuple(eqn.outvars[0].aval.shape)})")
        out1(g.add("Gather", [ins[0], idx_name],
                   [_attr_int("axis", axis)]))
    elif prim == "argmax":
        axes = eqn.params["axes"]
        am = g.add("ArgMax", ins[:1],
                   [_attr_int("axis", axes[0]), _attr_int("keepdims", 0)])
        # ONNX ArgMax always yields int64; cast to the jaxpr's dtype
        idx_dt = np.dtype(eqn.params.get("index_dtype", np.int64))
        if idx_dt != np.int64:
            am = g.add("Cast", [am],
                       [_attr_int("to", _np_dtype_code(idx_dt))])
        out1(am)
    elif prim == "cumsum":
        attrs = []
        if eqn.params.get("reverse"):
            attrs.append(_attr_int("reverse", 1))
        out1(g.add("CumSum", [
            ins[0], g.const(np.asarray(eqn.params["axis"], np.int64))],
            attrs))
    else:
        raise NotImplementedError(
            f"onnx export: primitive {prim!r} has no ONNX mapping (the "
            "supported subset covers Linear/Conv/pool/activation "
            "models; reference full exporter is paddle2onnx)")


def _emit_jaxpr(g: _Graph, jaxpr, names):
    # Literals are unhashable and handled inline by _emit_eqn's
    # hasattr(v, "val") path
    for eqn in jaxpr.eqns:
        _emit_eqn(g, eqn, names)


def export(layer, path, input_spec=None, opset_version=_OPSET, **configs):
    """Serialize ``layer`` to ``path + '.onnx'``.  input_spec: list of
    InputSpec/Tensors defining input shapes (required, like the
    reference exporter)."""
    import jax

    from ..core.tensor import Tensor
    from ..static import InputSpec

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    if not (_OPSET <= opset_version <= 17):
        raise ValueError(
            f"onnx.export emits opset-{_OPSET} constructs (ReduceSum/"
            "Squeeze axes-as-input, ReduceMax/Min axes-as-attribute — "
            "the latter is invalid from opset 18); opset_version must "
            f"be in [{_OPSET}, 17], got {opset_version}")
    specs = []
    for s in input_spec:
        if isinstance(s, InputSpec):
            if any(d in (None, -1) for d in s.shape):
                raise NotImplementedError(
                    "onnx.export traces static shapes; dynamic dims "
                    f"(None/-1) in InputSpec {list(s.shape)} are not "
                    "supported (they would silently bake as batch 1)")
            specs.append((tuple(int(d) for d in s.shape),
                          np.dtype(s.dtype)))
        else:
            arr = getattr(s, "_value", s)
            specs.append((tuple(arr.shape), np.dtype(str(arr.dtype))))

    layer.eval()
    params = list(layer.parameters()) + list(layer.buffers())

    def fn(pv, *xs):
        saved = [p._value for p in params]
        try:
            for p, a in zip(params, pv):
                p._value = a
            out = layer(*[Tensor(x) for x in xs])
            return out._value if isinstance(out, Tensor) else out
        finally:
            for p, s in zip(params, saved):
                p._value = s

    import jax.numpy as jnp
    p_vals = [p._value for p in params]
    in_structs = [jax.ShapeDtypeStruct(sh, dt) for sh, dt in specs]
    closed = jax.make_jaxpr(fn)(p_vals, *in_structs)
    jaxpr = closed.jaxpr

    g = _Graph()
    names = {}
    n_params = len(p_vals)
    for v, arr in zip(jaxpr.invars[:n_params], p_vals):
        names[v] = g.const(np.asarray(arr), "param")
    graph_inputs = []
    for i, (v, (sh, dt)) in enumerate(zip(jaxpr.invars[n_params:], specs)):
        nm = f"input_{i}"
        names[v] = nm
        graph_inputs.append(_value_info(nm, sh, dt))
    for cv, c in zip(jaxpr.constvars, closed.consts):
        names[cv] = g.const(np.asarray(c), "const")

    _emit_jaxpr(g, jaxpr, names)

    graph_outputs = []
    out_renames = []
    for i, ov in enumerate(jaxpr.outvars):
        nm = f"output_{i}"
        out_renames.append(_node("Identity", [names[ov]], [nm]))
        graph_outputs.append(_value_info(nm, ov.aval.shape,
                                         ov.aval.dtype))

    graph = b"".join(P.f_msg(1, n) for n in g.nodes + out_renames)
    graph += P.f_bytes(2, "paddle_tpu_graph")
    graph += b"".join(P.f_msg(5, t) for t in g.initializers)
    graph += b"".join(P.f_msg(11, vi) for vi in graph_inputs)
    graph += b"".join(P.f_msg(12, vo) for vo in graph_outputs)

    model = P.f_int(1, 8)                      # ir_version
    model += P.f_bytes(2, "paddle_tpu")        # producer_name
    model += P.f_msg(7, graph)
    model += P.f_msg(8, P.f_bytes(1, "") + P.f_int(2, opset_version))

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    import os
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    # every export self-checks against the vendored onnx.proto schema
    # (generic wire decoder, independent of this emitter — _schema.py)
    # BEFORE writing, so a failed export leaves no corrupt file behind
    from ._schema import validate
    validate(model)
    with open(out_path, "wb") as f:
        f.write(model)
    return out_path
