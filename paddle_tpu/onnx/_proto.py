"""Minimal protobuf wire-format encoder/decoder for the ONNX subset.

The environment has no ``onnx`` package (and none may be installed), so
the exporter writes the wire format directly — varints, length-delimited
submessages, 32-bit floats — exactly as protobuf serializes, and the
reader parses it back into {field_number: [values]} dicts.  Field
numbers follow onnx/onnx.proto (IR version 8 era).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Union

# wire types
_VARINT = 0
_I64 = 1
_LEN = 2
_I32 = 5


def _varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64  # protobuf encodes negatives as 10-byte varints
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def f_int(field: int, value: int) -> bytes:
    return tag(field, _VARINT) + _varint(int(value))


def f_bytes(field: int, value: Union[bytes, str]) -> bytes:
    if isinstance(value, str):
        value = value.encode("utf-8")
    return tag(field, _LEN) + _varint(len(value)) + value


def f_msg(field: int, encoded: bytes) -> bytes:
    return f_bytes(field, encoded)


def f_float(field: int, value: float) -> bytes:
    return tag(field, _I32) + struct.pack("<f", float(value))


def decode(buf: bytes) -> Dict[int, List]:
    """Parse one message level: {field: [raw values]} — varints as int,
    length-delimited as bytes (decode nested levels by calling again),
    32-bit as float."""
    out: Dict[int, List] = {}
    i = 0
    n = len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == _VARINT:
            v, i = _read_varint(buf, i)
        elif wire == _LEN:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wire == _I32:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wire == _I64:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(field, []).append(v)
    return out


def _read_varint(buf: bytes, i: int):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7
