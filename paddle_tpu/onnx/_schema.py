"""Structural validation of emitted ONNX bytes against the REAL
onnx.proto schema — independently of the emitter and of _runtime.py.

The checker has two parts:

1. a GENERIC protobuf wire-format reader (``_walk``): nothing in it
   knows about ONNX — it decodes tag varints, wire types, and
   length-delimited payloads exactly as the protobuf spec defines them,
   so a malformed varint, a wrong wire type, or a truncated
   length-delimited field fails here regardless of what the emitter
   thought it was writing;
2. a schema table (``_SCHEMA``) vendored from the official
   ``onnx/onnx.proto`` (field numbers, types, and labels of ModelProto,
   GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto,
   TypeProto, OperatorSetIdProto — onnx rev: opset-13-era IR v8).
   Every decoded field must appear in the table with the right wire
   type; message-typed fields recurse.

Because the table is transcribed from the upstream .proto (not from
_export.py), an emitter bug like "attribute ints written under the
wrong field number" or "missing AttributeProto.type discriminator"
fails validation even though the in-repo evaluator (written by the same
author) might happily accept it.  Semantic checks on top: graph
connectivity (every node input resolves), attribute payload matches its
declared type, initializer raw_data length == prod(dims) * dtype size.
"""

from __future__ import annotations

import struct

import numpy as np


# field kinds in the schema table
V = "varint"          # int32/int64/uint64/enum/bool
F = "fixed"           # float/double (we only emit varint+len, but the
                      # schema needs float fields for completeness)
S = "bytes"           # string/bytes
M = "msg"             # embedded message (recurse with the named schema)

# Vendored from the official onnx.proto (IR version 8 / opset 13 era).
_SCHEMA = {
    "ModelProto": {
        1: ("ir_version", V, None),
        8: ("opset_import", M, "OperatorSetIdProto"),
        2: ("producer_name", S, None),
        3: ("producer_version", S, None),
        4: ("domain", S, None),
        5: ("model_version", V, None),
        6: ("doc_string", S, None),
        7: ("graph", M, "GraphProto"),
        14: ("metadata_props", M, "StringStringEntryProto"),
        20: ("training_info", M, None),
        25: ("functions", M, None),
    },
    "OperatorSetIdProto": {
        1: ("domain", S, None),
        2: ("version", V, None),
    },
    "GraphProto": {
        1: ("node", M, "NodeProto"),
        2: ("name", S, None),
        5: ("initializer", M, "TensorProto"),
        15: ("sparse_initializer", M, None),
        10: ("doc_string", S, None),
        11: ("input", M, "ValueInfoProto"),
        12: ("output", M, "ValueInfoProto"),
        13: ("value_info", M, "ValueInfoProto"),
        14: ("quantization_annotation", M, None),
    },
    "NodeProto": {
        1: ("input", S, None),
        2: ("output", S, None),
        3: ("name", S, None),
        4: ("op_type", S, None),
        7: ("domain", S, None),
        5: ("attribute", M, "AttributeProto"),
        6: ("doc_string", S, None),
    },
    "AttributeProto": {
        1: ("name", S, None),
        21: ("ref_attr_name", S, None),
        13: ("doc_string", S, None),
        20: ("type", V, None),
        2: ("f", F, None),
        3: ("i", V, None),
        4: ("s", S, None),
        5: ("t", M, "TensorProto"),
        6: ("g", M, "GraphProto"),
        7: ("floats", F, None),
        8: ("ints", V, None),
        9: ("strings", S, None),
        10: ("tensors", M, "TensorProto"),
        11: ("graphs", M, "GraphProto"),
    },
    "TensorProto": {
        1: ("dims", V, None),
        2: ("data_type", V, None),
        3: ("segment", M, None),
        4: ("float_data", F, None),
        5: ("int32_data", V, None),
        6: ("string_data", S, None),
        7: ("int64_data", V, None),
        8: ("name", S, None),
        12: ("doc_string", S, None),
        9: ("raw_data", S, None),
        13: ("external_data", M, "StringStringEntryProto"),
        14: ("data_location", V, None),
        10: ("double_data", F, None),
        11: ("uint64_data", V, None),
    },
    "StringStringEntryProto": {
        1: ("key", S, None),
        2: ("value", S, None),
    },
    "ValueInfoProto": {
        1: ("name", S, None),
        2: ("type", M, "TypeProto"),
        3: ("doc_string", S, None),
    },
    "TypeProto": {
        1: ("tensor_type", M, "TypeProto.Tensor"),
        4: ("sequence_type", M, None),
        5: ("map_type", M, None),
        9: ("optional_type", M, None),
        8: ("sparse_tensor_type", M, None),
        6: ("denotation", S, None),
    },
    "TypeProto.Tensor": {
        1: ("elem_type", V, None),
        2: ("shape", M, "TensorShapeProto"),
    },
    "TensorShapeProto": {
        1: ("dim", M, "TensorShapeProto.Dimension"),
    },
    "TensorShapeProto.Dimension": {
        1: ("dim_value", V, None),
        2: ("dim_param", S, None),
        3: ("denotation", S, None),
    },
}

# AttributeProto.AttributeType enum (onnx.proto):
#   UNDEFINED=0 FLOAT=1 INT=2 STRING=3 TENSOR=4 GRAPH=5
#   FLOATS=6 INTS=7 STRINGS=8 TENSORS=9 GRAPHS=10
#   SPARSE_TENSOR=11 SPARSE_TENSORS=12 TYPE_PROTO=13 TYPE_PROTOS=14
_ATTR_TYPES = {
    1: ("FLOAT", "f"), 2: ("INT", "i"), 3: ("STRING", "s"),
    4: ("TENSOR", "t"), 5: ("GRAPH", "g"), 6: ("FLOATS", "floats"),
    7: ("INTS", "ints"), 8: ("STRINGS", "strings"),
    9: ("TENSORS", "tensors"), 10: ("GRAPHS", "graphs"),
    11: ("SPARSE_TENSOR", None), 13: ("TYPE_PROTO", None),
}

# TensorProto.DataType -> numpy itemsize (for raw_data length checks)
_DTYPE_SIZE = {1: 4, 2: 1, 3: 1, 4: 2, 5: 2, 6: 4, 7: 8, 9: 1, 10: 2,
               11: 8, 12: 4, 13: 8, 14: 8, 15: 16, 16: 2}


class OnnxSchemaError(ValueError):
    pass


def _read_varint(buf: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise OnnxSchemaError("truncated varint")
        b = buf[pos]
        out |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return out, pos
        shift += 7
        if shift > 70:
            raise OnnxSchemaError("varint too long")


def _walk(buf: bytes, schema_name: str, path: str = "$"):
    """Generic wire-format walk: decode every field, check it against
    the vendored schema, recurse into messages.  Returns
    {field_name: [decoded values]} — varints as int, bytes as bytes,
    messages as nested dicts."""
    schema = _SCHEMA[schema_name]
    out: dict = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if field == 0:
            raise OnnxSchemaError(f"{path}: field number 0 is invalid")
        if field not in schema:
            raise OnnxSchemaError(
                f"{path} ({schema_name}): unknown field number {field}")
        name, kind, sub = schema[field]
        if wire == 0:
            if kind not in (V,):
                raise OnnxSchemaError(
                    f"{path}.{name}: varint wire type for a {kind} field")
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            if kind not in (S, M, V, F):
                raise OnnxSchemaError(
                    f"{path}.{name}: length-delimited for {kind}")
            ln, pos = _read_varint(buf, pos)
            if pos + ln > len(buf):
                raise OnnxSchemaError(
                    f"{path}.{name}: length {ln} overruns buffer")
            payload = buf[pos:pos + ln]
            pos += ln
            if kind == M:
                if sub is None:
                    val = payload  # schema'd as opaque (unused by emitter)
                else:
                    val = _walk(payload, sub, f"{path}.{name}")
            elif kind == V:
                # packed repeated varints: decode each element
                vals, p2 = [], 0
                while p2 < len(payload):
                    v, p2 = _read_varint(payload, p2)
                    vals.append(v)
                out.setdefault(name, []).extend(vals)
                continue
            else:
                val = payload
        elif wire == 5:
            if kind != F:
                raise OnnxSchemaError(
                    f"{path}.{name}: fixed32 wire for a {kind} field")
            if pos + 4 > len(buf):
                raise OnnxSchemaError(f"{path}.{name}: truncated fixed32")
            val = struct.unpack("<f", buf[pos:pos + 4])[0]
            pos += 4
        elif wire == 1:
            if kind != F:
                raise OnnxSchemaError(
                    f"{path}.{name}: fixed64 wire for a {kind} field")
            if pos + 8 > len(buf):
                raise OnnxSchemaError(f"{path}.{name}: truncated fixed64")
            val = struct.unpack("<d", buf[pos:pos + 8])[0]
            pos += 8
        else:
            raise OnnxSchemaError(
                f"{path}.{name}: unsupported wire type {wire}")
        out.setdefault(name, []).append(val)
    return out


def validate(model_bytes: bytes) -> dict:
    """Full structural validation; returns a summary dict
    {nodes, initializers, inputs, outputs, opset} on success, raises
    OnnxSchemaError otherwise."""
    m = _walk(model_bytes, "ModelProto")
    if "ir_version" not in m:
        raise OnnxSchemaError("ModelProto.ir_version missing")
    if "graph" not in m:
        raise OnnxSchemaError("ModelProto.graph missing")
    if "opset_import" not in m:
        raise OnnxSchemaError("ModelProto.opset_import missing")
    opset = m["opset_import"][0]
    if "version" not in opset:
        raise OnnxSchemaError("OperatorSetIdProto.version missing")
    g = m["graph"][0]

    known = set()
    for t in g.get("initializer", []):
        if "name" not in t:
            raise OnnxSchemaError("initializer without name")
        if "data_type" not in t:
            raise OnnxSchemaError("initializer without data_type")
        dt = t["data_type"][0]
        if dt not in _DTYPE_SIZE:
            raise OnnxSchemaError(f"initializer dtype {dt} unknown")
        dims = [d for d in t.get("dims", [])]
        n = int(np.prod(dims)) if dims else 1
        raw = t.get("raw_data", [b""])[0]
        if len(raw) != n * _DTYPE_SIZE[dt]:
            raise OnnxSchemaError(
                f"initializer {t['name'][0]!r}: raw_data has {len(raw)} "
                f"bytes, dims {dims} x dtype {dt} needs "
                f"{n * _DTYPE_SIZE[dt]}")
        known.add(t["name"][0].decode())

    for vi in g.get("input", []):
        if "name" not in vi or "type" not in vi:
            raise OnnxSchemaError("graph input missing name/type")
        tt = vi["type"][0].get("tensor_type")
        if not tt or "elem_type" not in tt[0]:
            raise OnnxSchemaError(
                f"graph input {vi['name'][0]!r}: no tensor elem_type")
        known.add(vi["name"][0].decode())

    n_nodes = 0
    for node in g.get("node", []):
        n_nodes += 1
        if "op_type" not in node:
            raise OnnxSchemaError("node without op_type")
        op = node["op_type"][0].decode()
        for i in node.get("input", []):
            nm = i.decode()
            if nm and nm not in known:
                raise OnnxSchemaError(
                    f"node {op}: input {nm!r} is not a graph input, "
                    "initializer, or earlier node output (graph not "
                    "topologically valid)")
        if not node.get("output"):
            raise OnnxSchemaError(f"node {op}: no outputs")
        for o in node.get("output", []):
            known.add(o.decode())
        for a in node.get("attribute", []):
            if "name" not in a:
                raise OnnxSchemaError(f"node {op}: attribute without name")
            if "type" not in a:
                raise OnnxSchemaError(
                    f"node {op}: attribute {a['name'][0]!r} lacks the "
                    "type discriminator (required since IR v3)")
            at = a["type"][0]
            if at not in _ATTR_TYPES:
                raise OnnxSchemaError(
                    f"node {op}: attribute type {at} unknown")
            payload_field = _ATTR_TYPES[at][1]
            if payload_field and payload_field not in a:
                raise OnnxSchemaError(
                    f"node {op}: attribute {a['name'][0]!r} declares "
                    f"type {_ATTR_TYPES[at][0]} but field "
                    f"'{payload_field}' is absent")

    outs = g.get("output", [])
    if not outs:
        raise OnnxSchemaError("graph has no outputs")
    for vo in outs:
        nm = vo["name"][0].decode()
        if nm not in known:
            raise OnnxSchemaError(
                f"graph output {nm!r} is never produced")

    return {
        "nodes": n_nodes,
        "initializers": len(g.get("initializer", [])),
        "inputs": len(g.get("input", [])),
        "outputs": len(outs),
        "opset": opset["version"][0],
        "ir_version": m["ir_version"][0],
    }


def validate_file(path: str) -> dict:
    with open(path, "rb") as f:
        return validate(f.read())
