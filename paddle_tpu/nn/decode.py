"""Seq2seq decoding API: ``Decoder`` / ``BeamSearchDecoder`` /
``dynamic_decode``.

Reference parity: ``python/paddle/nn/decode.py`` (``BeamSearchDecoder``
:153, ``dynamic_decode`` :994) — the decoder-over-a-cell abstraction used
by seq2seq models, where beam search tiles the batch to
``[batch * beam]``, scores ``log_probs + step_log_probs``, selects top-k
over ``beam * vocab`` candidates, and reorders cell states by the chosen
parent beams.  ``finalize`` backtraces the beam tree (reference
``paddle.nn.functional.gather_tree``, a CUDA kernel there) to emit full
sequences.

TPU-first formulation:

- The beam-step math (log-softmax, score add, flat top-k, parent/token
  split, state gather) is pure ``jnp`` on static shapes — exactly the
  formulation that compiles well under jit; no dynamic beam widths.
- ``gather_tree`` is a REVERSE ``lax.scan`` over time with a batched
  gather per step (the CUDA kernel's per-thread pointer chase becomes a
  vectorized scan — same O(T·B·K) work, MXU-free, bandwidth-trivial).
- ``dynamic_decode`` runs the step loop eagerly with host-side early
  exit (each step is one compiled dispatch); the large-model compiled
  decode path is ``GenerationMixin.generate(num_beams=k)``, which runs
  the same beam-step math inside one ``lax.scan`` over a static KV
  cache (models/generation.py).
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]

_NEG_INF = 1e9


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _map(fn, nest):
    """tree-map over a (possibly nested) structure of Tensors."""
    return jax.tree_util.tree_map(
        fn, nest, is_leaf=lambda x: isinstance(x, Tensor))


class Decoder:
    """Decoding-step interface driven by ``dynamic_decode`` (reference
    ``python/paddle/nn/decode.py:42``): ``initialize`` -> repeated
    ``step`` -> optional ``finalize``."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoding over a cell (reference
    ``python/paddle/nn/decode.py:153``; see module docstring for the TPU
    formulation).

    ``cell(inputs, states) -> (outputs, next_states)`` is any RNN-cell-
    compatible callable; ``embedding_fn`` maps selected ids to the next
    inputs; ``output_fn`` maps cell outputs to logits.
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    # -- shape utilities (public API parity) --
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] with each entry repeated beam times
        (for tensors used inside the cell, e.g. attention memory)."""
        v = _unwrap(x)
        out = jnp.repeat(v, beam_size, axis=0)
        return Tensor(out) if isinstance(x, Tensor) else out

    def _split_batch_beams(self, v):
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    def _merge_batch_beams(self, v):
        return v.reshape((-1,) + v.shape[2:])

    def _expand_to_beam_size(self, v):
        return jnp.repeat(v[:, None], self.beam_size, axis=1)

    def _gather(self, v, beam_indices):
        """Reorder the beam axis of ``v [B, K, ...]`` by
        ``beam_indices [B, K]``."""
        b = v.shape[0]
        return v[jnp.arange(b)[:, None], beam_indices]

    # -- Decoder interface --
    def initialize(self, initial_cell_states):
        cell_states = _map(lambda t: self._expand_to_beam_size(_unwrap(t)),
                           initial_cell_states)
        first = jax.tree_util.tree_leaves(cell_states)[0]
        batch = first.shape[0]
        k = self.beam_size
        init_inputs = jnp.full((batch, k), self.start_token, jnp.int32)
        # only beam 0 is live initially, others at -inf so the first
        # top-k picks k DISTINCT tokens from beam 0
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-_NEG_INF] * (k - 1)], jnp.float32),
            (batch, 1))
        finished = jnp.zeros((batch, k), bool)
        lengths = jnp.zeros((batch, k), jnp.int32)
        states = self.StateWrapper(cell_states, log_probs, finished,
                                   lengths)
        inputs = (self.embedding_fn(Tensor(init_inputs))
                  if self.embedding_fn else Tensor(init_inputs))
        return inputs, states, Tensor(finished)

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        """Score candidates and pick the next beams; all-jnp.  logits:
        [B, K, V]."""
        b, k, vocab = logits.shape
        step_lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        # finished beams may only continue with end_token at zero cost
        noend = jnp.full((vocab,), -_NEG_INF, jnp.float32)
        noend = noend.at[self.end_token].set(0.0)
        step_lp = jnp.where(beam_state.finished[:, :, None],
                            noend[None, None, :], step_lp)
        total = beam_state.log_probs[:, :, None] + step_lp      # [B,K,V]
        flat = total.reshape(b, k * vocab)
        topk_scores, topk_idx = jax.lax.top_k(flat, k)          # [B,K]
        beam_idx = topk_idx // vocab
        token_idx = (topk_idx % vocab).astype(jnp.int32)
        next_cell_states = _map(lambda v: self._gather(v, beam_idx),
                                next_cell_states)
        prev_finished = self._gather(beam_state.finished, beam_idx)
        lengths = self._gather(beam_state.lengths, beam_idx)
        lengths = lengths + (~prev_finished).astype(jnp.int32)
        finished = prev_finished | (token_idx == self.end_token)
        out = self.OutputWrapper(topk_scores, token_idx,
                                 beam_idx.astype(jnp.int32))
        state = self.StateWrapper(next_cell_states, topk_scores, finished,
                                  lengths)
        return out, state

    def step(self, time, inputs, states, **kwargs):
        merged_inputs = _map(
            lambda t: Tensor(self._merge_batch_beams(_unwrap(t))), inputs)
        merged_cell_states = _map(
            lambda v: Tensor(self._merge_batch_beams(v)),
            states.cell_states)
        cell_out, next_cell_states = self.cell(merged_inputs,
                                               merged_cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = self._split_batch_beams(_unwrap(cell_out))
        next_cell_states = _map(
            lambda t: self._split_batch_beams(_unwrap(t)),
            next_cell_states)
        out, state = self._beam_search_step(time, logits, next_cell_states,
                                            states)
        sample_ids = Tensor(out.predicted_ids)
        next_inputs = (self.embedding_fn(sample_ids) if self.embedding_fn
                       else sample_ids)
        return out, state, next_inputs, Tensor(state.finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace the beam tree into full sequences
        ([T, B, K] int64)."""
        from .functional import gather_tree
        predicted = gather_tree(Tensor(outputs.predicted_ids),
                                Tensor(outputs.parent_ids))
        return predicted, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder.step`` until every sequence finishes or
    ``max_step_num`` steps (reference ``python/paddle/nn/decode.py:994``).

    Each step is one compiled dispatch; the loop exits early on a
    host-side all-finished check (the per-step device->host sync is the
    eager API's contract — the fully-compiled path is
    ``GenerationMixin.generate``).
    """
    inputs, states, finished = decoder.initialize(inits)
    finished_v = _unwrap(finished).astype(bool)
    batch_shape = finished_v.shape
    seq_lens = jnp.zeros(batch_shape, jnp.int32)
    step_outputs = []
    step = 0
    limit = int(max_step_num) if max_step_num is not None else 10 ** 9

    while True:
        out, next_states, next_inputs, next_finished = decoder.step(
            Tensor(jnp.asarray([step], jnp.int32)), inputs, states,
            **kwargs)
        next_finished_v = _unwrap(next_finished).astype(bool)
        if not decoder.tracks_own_finished:
            next_finished_v = next_finished_v | finished_v
            if impute_finished:
                # copy states through for already-finished entries; a
                # decoder that tracks its own finished (beam search)
                # reorders states itself, so imputation applies only
                # here (reference decode.py:734 nests it the same way)
                def _impute(new, old):
                    nv, ov = _unwrap(new), _unwrap(old)
                    mask = finished_v.reshape(
                        finished_v.shape
                        + (1,) * (nv.ndim - finished_v.ndim))
                    return jnp.where(mask, ov, nv)
                next_states = jax.tree_util.tree_map(
                    _impute, next_states, states,
                    is_leaf=lambda x: isinstance(x, Tensor))
            seq_lens = seq_lens + (~finished_v).astype(jnp.int32)
        else:
            # the decoder's own state carries the true lengths
            # (reference decode.py:744)
            seq_lens = _unwrap(getattr(next_states, "lengths", seq_lens))
        step_outputs.append(_map(_unwrap, out))
        inputs, states, finished_v = next_inputs, next_states, \
            next_finished_v
        step += 1
        if step > limit or bool(next_finished_v.all()):
            break

    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0),
                                     *step_outputs)
    if hasattr(decoder, "finalize") and not isinstance(
            getattr(type(decoder), "finalize", None), property):
        try:
            final_outputs, final_states = decoder.finalize(
                stacked, states, Tensor(seq_lens))
        except NotImplementedError:
            final_outputs, final_states = stacked, states
    else:
        final_outputs, final_states = stacked, states

    def _to_batch_major(v):
        av = _unwrap(v)
        if av.ndim < 2:
            return Tensor(av)
        return Tensor(jnp.swapaxes(av, 0, 1))

    if not output_time_major:
        final_outputs = _map(_to_batch_major, final_outputs)
    final_outputs = _map(
        lambda v: v if isinstance(v, Tensor) else Tensor(jnp.asarray(v)),
        final_outputs)
    final_states = _map(
        lambda v: v if isinstance(v, Tensor) else Tensor(jnp.asarray(v)),
        final_states)
    if return_length:
        return final_outputs, final_states, Tensor(seq_lens)
    return final_outputs, final_states
