"""Initializers (analogue of python/paddle/nn/initializer/)."""

from __future__ import annotations

import math

import jax.numpy as jnp
import jax.random as jrandom
import numpy as np

from ...core.generator import default_generator

__all__ = [
    "Constant", "Normal", "TruncatedNormal", "Uniform", "XavierNormal",
    "XavierUniform", "KaimingNormal", "KaimingUniform", "Assign", "Orthogonal",
    "Dirac", "calculate_gain", "set_global_initializer",
]


def calculate_gain(nonlinearity, param=None):
    gains = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        return self.mean + self.std * jrandom.normal(key, shape, jnp.float32)\
            .astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        raw = jrandom.truncated_normal(key, self.a, self.b, shape, jnp.float32)
        return (self.mean + self.std * raw).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        return jrandom.uniform(key, shape, jnp.float32, self.low, self.high)\
            .astype(dtype)


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # linear weight [in, out] (reference convention)
        return shape[0], shape[1]
    # conv weight [out, in/groups, *k]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = default_generator().next_key()
        return (std * jrandom.normal(key, shape, jnp.float32)).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = default_generator().next_key()
        return jrandom.uniform(key, shape, jnp.float32, -limit, limit)\
            .astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        key = default_generator().next_key()
        return (std * jrandom.normal(key, shape, jnp.float32)).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        key = default_generator().next_key()
        return jrandom.uniform(key, shape, jnp.float32, -limit, limit)\
            .astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ...core.tensor import Tensor
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype=dtype).reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        key = default_generator().next_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = jrandom.normal(key, (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols].reshape(shape)).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                idx = (g * (oc // self.groups) + i, i) + tuple(centers)
                out[idx] = 1.0
        return jnp.asarray(out, dtype=dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init
