"""Gradient clipping (analogue of python/paddle/nn/clip.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip.  In hybrid-parallel runs the distributed optimizer
    extends the squared-norm sum across mesh axes (reference:
    HybridParallelClipGrad, hybrid_parallel_optimizer.py:45)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = clip_norm

    def _global_norm_sq(self, params_grads):
        sq = None
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            s = jnp.sum(jnp.square(g._value.astype(jnp.float32)))
            sq = s if sq is None else sq + s
        return sq

    def _apply_scale(self, params_grads, sq):
        """Scale every clippable grad by clip_norm / max(||g||, clip_norm)
        computed from the given squared global norm."""
        global_norm = jnp.sqrt(sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out

    def _clip(self, params_grads):
        sq = self._global_norm_sq(params_grads)
        if sq is None:
            return params_grads
        return self._apply_scale(params_grads, sq)


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    grads = [p._grad for p in params if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in params:
        if p._grad is not None:
            p._grad.set_value(p._grad._value * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    params = [parameters] if isinstance(parameters, Tensor) else list(parameters)
    for p in params:
        if p._grad is not None:
            p._grad.set_value(jnp.clip(p._grad._value, -clip_value, clip_value))
