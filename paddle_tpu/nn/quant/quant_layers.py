"""QAT wrapper layers and converted int8 inference layers.

Reference parity: ``python/paddle/nn/quant/quant_layers.py``
(QuantizedLinear/QuantizedConv2D with fake-quant on weight+activation).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer.layers import Layer
from .. import functional as F


class QuantStub(Layer):
    """Marks an activation quantization point; holds the act quanter."""

    def __init__(self, quanter):
        super().__init__()
        self.quanter = quanter

    def forward(self, x):
        return self.quanter(x) if self.quanter is not None else x


class QuantedLinear(Layer):
    """Linear with fake-quantized weight and (optionally) activation."""

    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        # keep a plain reference to the float layer (not a registered
        # sublayer — its weight/bias are re-registered on this wrapper and
        # must not appear twice in parameters())
        object.__setattr__(self, "_float_layer", layer)
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        object.__setattr__(self, "_float_layer", layer)
        self.weight = layer.weight
        self.bias = layer.bias
        self._stride = layer.stride
        self._padding = layer.padding
        self._dilation = layer.dilation
        self._groups = layer.groups
        self._data_format = layer.data_format
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.conv2d(x, w, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


def _dequant(qw, scale, axis):
    shape = [1] * qw.ndim
    shape[axis % qw.ndim] = -1
    return qw.astype(jnp.float32) * jnp.asarray(scale, jnp.float32).reshape(shape)


class QuantizedLinearInfer(Layer):
    """Converted inference Linear: int8 weight + per-channel scales."""

    def __init__(self, qweight, scales, bias, in_features, out_features,
                 act_scale=None, bits=8):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))
        self.register_buffer("weight_scale", Tensor(scales))
        self.bias = bias
        self.in_features = in_features
        self.out_features = out_features
        self._act_scale = act_scale
        self._bits = bits

    def forward(self, x):
        from ...ops.pallas import quantized_matmul as pallas_qmm
        # Pallas qmm only at decode-sized M (it re-streams the weight per
        # M-block — see should_use_pallas); larger M takes XLA's fused
        # int8-upcast matmul, which reads the int8 weight once
        if pallas_qmm.should_use_pallas(x, self.qweight, max_m=64):
            from ...core.dispatch import dispatch
            has_bias = self.bias is not None

            def impl(a, qw, s, *rest):
                out = pallas_qmm.quantized_matmul(a, qw, s)
                if rest:
                    out = out + rest[0].astype(out.dtype)
                return out

            args = (x, self.qweight, self.weight_scale) + \
                ((self.bias,) if has_bias else ())
            mask = [False, True, True] + ([False] if has_bias else [])
            return dispatch("quantized_linear", impl, args,
                            nondiff_mask=mask)
        # dequant INTO the activation dtype: bf16 activations keep the
        # MXU at bf16 rate and XLA fuses the int8 read + upcast into the
        # dot (an f32 dequant would halve matmul rate and double bytes)
        xv = x._value if hasattr(x, "_value") else x
        w = Tensor(_dequant(self.qweight._value, self.weight_scale._value,
                            axis=-1).astype(xv.dtype))
        return F.linear(x, w, self.bias)


class QuantizedConv2DInfer(Layer):
    def __init__(self, qweight, scales, bias, conv_args, act_scale=None,
                 bits=8):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))
        self.register_buffer("weight_scale", Tensor(scales))
        self.bias = bias
        (self._stride, self._padding, self._dilation, self._groups,
         self._data_format) = conv_args
        self._act_scale = act_scale
        self._bits = bits

    def forward(self, x):
        w = Tensor(_dequant(self.qweight._value, self.weight_scale._value,
                            axis=0))
        return F.conv2d(x, w, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)
