"""QAT wrapper layers and converted int8 inference layers.

Reference parity: ``python/paddle/nn/quant/quant_layers.py``
(QuantizedLinear/QuantizedConv2D with fake-quant on weight+activation).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ..layer.layers import Layer
from .. import functional as F


class QuantStub(Layer):
    """Marks an activation quantization point; holds the act quanter."""

    def __init__(self, quanter):
        super().__init__()
        self.quanter = quanter

    def forward(self, x):
        return self.quanter(x) if self.quanter is not None else x


class QuantedLinear(Layer):
    """Linear with fake-quantized weight and (optionally) activation."""

    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        # keep a plain reference to the float layer (not a registered
        # sublayer — its weight/bias are re-registered on this wrapper and
        # must not appear twice in parameters())
        object.__setattr__(self, "_float_layer", layer)
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, act_quanter=None, weight_quanter=None):
        super().__init__()
        object.__setattr__(self, "_float_layer", layer)
        self.weight = layer.weight
        self.bias = layer.bias
        self._stride = layer.stride
        self._padding = layer.padding
        self._dilation = layer.dilation
        self._groups = layer.groups
        self._data_format = layer.data_format
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.conv2d(x, w, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


def _dequant(qw, scale, axis):
    shape = [1] * qw.ndim
    shape[axis % qw.ndim] = -1
    return qw.astype(jnp.float32) * jnp.asarray(scale, jnp.float32).reshape(shape)


class QuantizedLinearInfer(Layer):
    """Converted inference Linear: int8 weight + per-channel scales."""

    def __init__(self, qweight, scales, bias, in_features, out_features,
                 act_scale=None, bits=8):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))
        self.register_buffer("weight_scale", Tensor(scales))
        self.bias = bias
        self.in_features = in_features
        self.out_features = out_features
        self._act_scale = act_scale
        self._bits = bits
        # a following activation folded into the kernel epilogue by
        # quantization.fuse_act_into_quant_linear ("gelu"/"relu"/"silu");
        # the fused form is inference-only (no custom vjp)
        self._fused_act = None

    def forward(self, x):
        from ...ops.pallas import quantized_matmul as pallas_qmm
        fused_act = self._fused_act
        use_fused_kernel = bool(fused_act)
        if fused_act and isinstance(x, Tensor) and not x.stop_gradient:
            from ...core.tape import is_grad_enabled
            if is_grad_enabled():
                # the fused-epilogue kernel has no vjp; an all-nondiff
                # dispatch would return detached outputs and silently
                # sever upstream gradients — take the differentiable
                # dequant+linear+act fallback instead (same math, the
                # XLA path)
                use_fused_kernel = False
        # Pallas qmm at decode-sized M always (it re-streams the weight
        # per M-block — see should_use_pallas); with a fused epilogue the
        # kernel also wins at serving M (the custom call is a fusion
        # barrier, so XLA's path materializes the epilogue between
        # kernels) — measured in BASELINE.md's int8 serving section.
        # Capped at 512 rows: beyond that the per-M-block weight
        # re-stream (the 13x prefill regression) outweighs the epilogue
        max_m = 512 if use_fused_kernel else 64
        if (use_fused_kernel or not fused_act) and \
                pallas_qmm.should_use_pallas(x, self.qweight, max_m=max_m):
            from ...core.dispatch import dispatch
            has_bias = self.bias is not None

            def impl(a, qw, s, *rest):
                if fused_act:
                    return pallas_qmm.quantized_matmul(
                        a, qw, s, bias=rest[0] if rest else None,
                        act=fused_act)
                out = pallas_qmm.quantized_matmul(a, qw, s)
                if rest:
                    out = out + rest[0].astype(out.dtype)
                return out

            args = (x, self.qweight, self.weight_scale) + \
                ((self.bias,) if has_bias else ())
            if fused_act:
                # inference-only: the fused-epilogue kernel has no vjp,
                # so every input is nondiff (a requires-grad bias would
                # otherwise pull jax.vjp through the pallas call)
                mask = [True] * len(args)
            else:
                mask = [False, True, True] + ([False] if has_bias else [])
            return dispatch("quantized_linear", impl, args,
                            nondiff_mask=mask)
        # dequant INTO the activation dtype: bf16 activations keep the
        # MXU at bf16 rate and XLA fuses the int8 read + upcast into the
        # dot (an f32 dequant would halve matmul rate and double bytes)
        xv = x._value if hasattr(x, "_value") else x
        w = Tensor(_dequant(self.qweight._value, self.weight_scale._value,
                            axis=-1).astype(xv.dtype))
        out = F.linear(x, w, self.bias)
        if fused_act:
            # approximate=True matches the kernel epilogue's tanh GELU —
            # outputs must not depend on which path the batch size takes
            out = {"gelu": lambda t: F.gelu(t, True), "relu": F.relu,
                   "silu": F.silu}[fused_act](out)
        return out


class QuantizedConv2DInfer(Layer):
    def __init__(self, qweight, scales, bias, conv_args, act_scale=None,
                 bits=8):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))
        self.register_buffer("weight_scale", Tensor(scales))
        self.bias = bias
        (self._stride, self._padding, self._dilation, self._groups,
         self._data_format) = conv_args
        self._act_scale = act_scale
        self._bits = bits

    def forward(self, x):
        w = Tensor(_dequant(self.qweight._value, self.weight_scale._value,
                            axis=0))
        return F.conv2d(x, w, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)
