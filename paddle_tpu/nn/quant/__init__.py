"""Quantization-aware layers (reference: ``python/paddle/nn/quant/``).

``QuantedLinear``/``QuantedConv2D`` wrap a float layer with weight and
activation fake-quanters during QAT; ``QuantizedLinearInfer``/
``QuantizedConv2DInfer`` are the converted inference forms holding int8
weights + scales and dequantizing on the fly (XLA fuses the dequant into
the matmul/conv epilogue on TPU).
"""

from .quant_layers import (QuantedLinear, QuantedConv2D,
                           QuantizedLinearInfer, QuantizedConv2DInfer,
                           QuantStub)

__all__ = ["QuantedLinear", "QuantedConv2D", "QuantizedLinearInfer",
           "QuantizedConv2DInfer", "QuantStub"]
