"""Loss functionals (analogue of python/paddle/nn/functional/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch
from ...core.tensor import Tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "mse_loss",
    "l1_loss", "smooth_l1_loss", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "kl_div", "margin_ranking_loss",
    "hinge_embedding_loss", "cosine_embedding_loss", "triplet_margin_loss",
    "log_loss", "square_error_cost", "sigmoid_focal_loss", "dice_loss",
    "ctc_loss", "rnnt_loss", "poisson_nll_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
]


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True,
                  label_smoothing=0.0, name=None):
    def impl(logits, lbl, *rest):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax \
            else jnp.log(jnp.maximum(logits, 1e-30))
        n_classes = logits.shape[axis]
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            soft = lbl
            if label_smoothing > 0.0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            return _reduce_loss(loss, reduction)
        idx = lbl.astype(jnp.int32)
        squeeze = False
        if idx.ndim == logits.ndim:  # trailing [..., 1] label layout
            idx = jnp.squeeze(idx, axis=axis)
            squeeze = True
        if label_smoothing > 0.0:
            soft = jax.nn.one_hot(idx, n_classes, axis=axis, dtype=logp.dtype)
            soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            safe_idx = jnp.where(idx == ignore_index, 0, idx)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_idx, axis), axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
        valid = idx != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if rest:  # class weights
            w = rest[0]
            sample_w = jnp.where(valid, jnp.take(w, jnp.where(valid, idx, 0)), 0.0)
            loss = loss * sample_w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(sample_w), 1e-12)
        if reduction == "mean":
            n_valid = jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
            return jnp.sum(loss) / n_valid
        if squeeze and reduction == "none":
            loss = jnp.expand_dims(loss, axis)
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("cross_entropy", impl, args,
                    nondiff_mask=[False, True] + [False] * (len(args) - 2))


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    if return_softmax:
        from .activation import softmax
        return loss, softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return _nll(input, label, weight, ignore_index, reduction)


def _nll(input, label, weight, ignore_index, reduction):
    def impl(logp, lbl, *rest):
        idx = lbl.astype(jnp.int32)
        safe_idx = jnp.where(idx == ignore_index, 0, idx)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_idx, 1), axis=1)
        loss = -jnp.squeeze(picked, axis=1)
        valid = idx != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if rest:
            w = rest[0]
            sw = jnp.where(valid, jnp.take(w, safe_idx), 0.0)
            loss = loss * sw
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(sw), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(
                jnp.sum(valid.astype(logp.dtype)), 1.0)
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("nll_loss", impl, args,
                    nondiff_mask=[False, True] + [False] * (len(args) - 2))


def mse_loss(input, label, reduction="mean", name=None):
    return dispatch("mse_loss",
                    lambda a, b: _reduce_loss(jnp.square(a - b), reduction),
                    (input, label))


def l1_loss(input, label, reduction="mean", name=None):
    return dispatch("l1_loss",
                    lambda a, b: _reduce_loss(jnp.abs(a - b), reduction),
                    (input, label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
        # paddle: huber with delta both threshold and scale
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce_loss(loss, reduction)

    return dispatch("smooth_l1_loss", impl, (input, label))


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def impl(p, y, *rest):
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(p, eps)) +
                 (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if rest:
            loss = loss * rest[0]
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("binary_cross_entropy", impl, args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def impl(z, y, *rest):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        # numerically stable BCE-with-logits
        neg_abs = -jnp.abs(z)
        log1p = jnp.log1p(jnp.exp(neg_abs))
        if pw is None:
            loss = jnp.maximum(z, 0) - z * y + log1p
        else:
            log_sig = -jax.nn.softplus(-z)
            log_one_minus = -z - jax.nn.softplus(-z)
            loss = -(pw * y * log_sig + (1 - y) * log_one_minus)
        if w is not None:
            loss = loss * w
        return _reduce_loss(loss, reduction)

    args = (logit, label)
    if weight is not None:
        args += (weight,)
    if pos_weight is not None:
        args += (pos_weight,)
    return dispatch("bce_with_logits", impl, args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def impl(logp, target):
        if log_target:
            loss = jnp.exp(target) * (target - logp)
        else:
            safe_t = jnp.maximum(target, 1e-12)
            loss = target * (jnp.log(safe_t) - logp)
            loss = jnp.where(target > 0, loss, 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce_loss(loss, reduction)

    return dispatch("kl_div", impl, (input, label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def impl(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce_loss(loss, reduction)

    return dispatch("margin_ranking_loss", impl, (input, other, label),
                    nondiff_mask=[False, False, True])


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def impl(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce_loss(loss, reduction)

    return dispatch("hinge_embedding_loss", impl, (input, label),
                    nondiff_mask=[False, True])


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce_loss(loss, reduction)

    return dispatch("cosine_embedding_loss", impl, (input1, input2, label),
                    nondiff_mask=[False, False, True])


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)

        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce_loss(loss, reduction)

    return dispatch("triplet_margin_loss", impl, (input, positive, negative))


def log_loss(input, label, epsilon=1e-4, name=None):
    def impl(p, y):
        return -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon))

    return dispatch("log_loss", impl, (input, label))


def square_error_cost(input, label, name=None):
    return dispatch("square_error_cost",
                    lambda a, b: jnp.square(a - b), (input, label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def impl(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce_loss(loss, reduction)

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return dispatch("sigmoid_focal_loss", impl, args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def impl(p, y):
        oh = jax.nn.one_hot(jnp.squeeze(y, -1).astype(jnp.int32),
                            p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * oh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(oh, axis=reduce_dims)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return dispatch("dice_loss", impl, (input, label),
                    nondiff_mask=[False, True])


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def impl(a, y):
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(
                2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce_loss(loss, reduction)

    return dispatch("poisson_nll_loss", impl, (input, label),
                    nondiff_mask=[False, True])


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    def impl(z, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        loss = jnp.mean(loss, axis=-1)
        if rest:
            loss = loss * rest[0]
        return _reduce_loss(loss, reduction)

    args = (input, label) + ((weight,) if weight is not None else ())
    return dispatch("multi_label_soft_margin_loss", impl, args,
                    nondiff_mask=[False, True] + [False] * (len(args) - 2))


def soft_margin_loss(input, label, reduction="mean", name=None):
    def impl(z, y):
        return _reduce_loss(jnp.log1p(jnp.exp(-y * z)), reduction)

    return dispatch("soft_margin_loss", impl, (input, label),
                    nondiff_mask=[False, True])


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via dynamic-programming in lax.scan (reference: warpctc binding
    ``paddle/phi/kernels/gpu/warpctc_kernel.cu``)."""

    def impl(lp, lbl, in_len, lbl_len):
        # lp: [T, B, C] log-probs (paddle layout); convert to [B, T, C]
        lp_b = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        B, T, C = lp_b.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended label seq with blanks: [B, S]
        ext = jnp.full((B, S), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        neg_inf = -1e30

        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp_b[:, 0, blank])
        first_lbl = jnp.take_along_axis(
            lp_b[:, 0, :], ext[:, 1:2], axis=1)[:, 0]
        alpha0 = alpha0.at[:, 1].set(first_lbl)

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            probs_t = jnp.take_along_axis(lp_b[:, t, :], ext, axis=1)
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((B, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            m = jnp.maximum(jnp.maximum(a_prev, a_shift1), a_shift2)
            new = m + jnp.log(
                jnp.exp(a_prev - m) + jnp.exp(a_shift1 - m) +
                jnp.exp(a_shift2 - m) + 1e-37)
            new = new + probs_t
            # mask time steps beyond input length
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = 2 * lbl_len.astype(jnp.int32)
        end2 = end1 - 1
        ll1 = jnp.take_along_axis(alpha, end1[:, None], axis=1)[:, 0]
        ll2 = jnp.take_along_axis(alpha, jnp.maximum(end2, 0)[:, None], axis=1)[:, 0]
        m = jnp.maximum(ll1, ll2)
        ll = m + jnp.log(jnp.exp(ll1 - m) + jnp.exp(ll2 - m))
        loss = -ll
        if norm_by_times:
            loss = loss / in_len.astype(loss.dtype)
        if reduction == "mean":
            return jnp.mean(loss / lbl_len.astype(loss.dtype))
        return _reduce_loss(loss, reduction)

    return dispatch("ctc_loss", impl,
                    (log_probs, labels, input_lengths, label_lengths),
                    nondiff_mask=[False, True, True, True])


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-Transducer loss as a lax.scan dynamic program (reference:
    ``python/paddle/nn/functional/loss.py:1955`` binding the external
    warp-transducer library via ``phi/kernels/impl/warprnnt_kernel_impl.h``).

    ``input``: [B, Tmax, Umax+1, V] UNNORMALIZED logits — like
    warp-transducer, log_softmax is applied internally.  ``label``:
    [B, Umax] int; per-sequence lengths in ``input_lengths`` /
    ``label_lengths``.

    DP formulation (one scan over T, inner scan over U for the
    within-row label recurrence — the lattice cell (t, u) sums the
    blank arc from (t-1, u) and the label arc from (t, u-1)):

        alpha[0, 0] = 0
        alpha[0, u] = alpha[0, u-1] + lp_label[0, u-1]
        alpha[t, u] = logaddexp(alpha[t-1, u] + lp_blank[t-1, u],
                                alpha[t, u-1] + lp_label[t, u-1])
        loss = -(alpha[T-1, U] + lp_blank[T-1, U])

    FastEmit (arXiv:2010.11148) follows warp-transducer's formulation —
    label-emission GRADIENTS scale by (1 + lambda) while the loss value
    is the standard NLL; implemented as the STE-style
    ``lp + lambda*(lp - stop_gradient(lp))`` on the label arcs.
    Gradients w.r.t. ``input`` flow through the scans via autodiff (the
    reference ships a hand-written backward kernel instead).
    """
    if reduction not in ("none", "mean", "sum"):
        raise ValueError(
            f"rnnt_loss reduction must be none/mean/sum, got {reduction!r}")

    def impl(acts, lbl, in_len, lbl_len):
        if acts.ndim != 4:
            raise ValueError(
                f"rnnt_loss input must be [B, Tmax, Umax+1, V], got "
                f"rank {acts.ndim}")
        B, T, U1, V = acts.shape
        U = U1 - 1
        if lbl.shape != (B, U):
            raise ValueError(
                f"rnnt_loss label must be [B, {U}] for input U+1={U1}, "
                f"got {list(lbl.shape)}")
        lp = jax.nn.log_softmax(acts.astype(jnp.float32), axis=-1)
        lp_blank = lp[:, :, :, blank]                       # [B, T, U+1]
        # label arc at (t, u) consumes label[u]: [B, T, U]
        lbl_idx = jnp.broadcast_to(lbl.astype(jnp.int32)[:, None, :, None],
                                   (B, T, U, 1))
        lp_label = jnp.take_along_axis(lp[:, :, :U, :], lbl_idx,
                                       axis=3)[..., 0]
        if fastemit_lambda:
            lp_label = lp_label + fastemit_lambda * (
                lp_label - jax.lax.stop_gradient(lp_label))

        # t = 0 row: pure label arcs
        alpha0 = jnp.concatenate(
            [jnp.zeros((B, 1), lp.dtype),
             jnp.cumsum(lp_label[:, 0, :], axis=1)], axis=1)  # [B, U+1]

        def row(alpha_prev, t):
            from_blank = alpha_prev + lp_blank[:, t - 1, :]   # [B, U+1]
            lab_t = lp_label[:, t, :]                         # [B, U]

            def cell(a, u):
                a = jnp.logaddexp(from_blank[:, u], a + lab_t[:, u - 1])
                return a, a

            _, rest = jax.lax.scan(cell, from_blank[:, 0],
                                   jnp.arange(1, U1))
            new = jnp.concatenate(
                [from_blank[:, :1], rest.T], axis=1) if U else from_blank
            return new, new

        _, rows = jax.lax.scan(row, alpha0, jnp.arange(1, T))
        all_rows = jnp.concatenate([alpha0[None], rows], axis=0)  # [T,B,U+1]

        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        u_idx = jnp.clip(lbl_len.astype(jnp.int32), 0, U)
        barange = jnp.arange(B)
        alpha_final = all_rows[t_idx, barange, u_idx]
        final_blank = lp_blank[barange, t_idx, u_idx]
        loss = -(alpha_final + final_blank)
        return _reduce_loss(loss.astype(acts.dtype), reduction)

    return dispatch("rnnt_loss", impl,
                    (input, label, input_lengths, label_lengths),
                    nondiff_mask=[False, True, True, True])
