"""Pooling (analogue of python/paddle/nn/functional/pooling.py) via
``lax.reduce_window`` (VPU-native windowed reductions)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import dispatch

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in (list(v) * n if len(v) == 1 else v))
    return (int(v),) * n


def _pool(x, kernel, stride, padding, n_spatial, kind, data_format,
          ceil_mode=False, exclusive=True, name="pool"):
    ks = _tup(kernel, n_spatial)
    st = _tup(stride if stride is not None else kernel, n_spatial)
    if isinstance(padding, str):
        pad_cfg = padding.upper()
    else:
        p = _tup(padding, n_spatial)
        pad_cfg = [(i, i) for i in p]

    channels_first = data_format.startswith("NC")

    def impl(a):
        if channels_first:
            window = (1, 1) + ks
            strides = (1, 1) + st
            pads = [(0, 0), (0, 0)] + (pad_cfg if isinstance(pad_cfg, list) else [])
        else:
            window = (1,) + ks + (1,)
            strides = (1,) + st + (1,)
            pads = [(0, 0)] + (pad_cfg if isinstance(pad_cfg, list) else []) + [(0, 0)]
        if isinstance(pad_cfg, str):
            pads = pad_cfg
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, init, jax.lax.max, window, strides, pads)
        # avg
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add,
                                       window, strides, pads)
        if exclusive and not isinstance(pads, str):
            ones = jnp.ones_like(a)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                           window, strides, pads)
            return summed / counts
        return summed / float(np.prod(ks))

    return dispatch(name, impl, (x,))


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", "NCL",
                 ceil_mode, exclusive, "avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", data_format,
                 ceil_mode, exclusive, "avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", data_format,
                 ceil_mode, exclusive, "avg_pool3d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    return _pool(x, kernel_size, stride, padding, 1, "max", "NCL",
                 ceil_mode, name="max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "max", data_format,
                 ceil_mode, name="max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", data_format,
                 ceil_mode, name="max_pool3d")


def _adaptive(x, output_size, n_spatial, kind, name, spatial_start=2):
    def impl(a):
        ss = spatial_start
        spatial = a.shape[ss:ss + n_spatial]
        os = _tup(output_size, n_spatial)
        os = tuple(o if o is not None else s for o, s in zip(os, spatial))
        out = a
        # pool each spatial dim independently with computed windows
        for d in range(n_spatial):
            in_s, out_s = out.shape[ss + d], os[d]
            if in_s == out_s:
                continue
            if in_s % out_s == 0:
                k = in_s // out_s
                window = [1] * out.ndim
                strides = [1] * out.ndim
                window[ss + d] = k
                strides[ss + d] = k
                if kind == "max":
                    out = jax.lax.reduce_window(
                        out, -jnp.inf, jax.lax.max, tuple(window),
                        tuple(strides), "VALID")
                else:
                    out = jax.lax.reduce_window(
                        out, 0.0, jax.lax.add, tuple(window), tuple(strides),
                        "VALID") / k
            else:
                # general adaptive: gather per output index
                starts = (np.arange(out_s) * in_s // out_s)
                ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
                slices = []
                moved = jnp.moveaxis(out, ss + d, 0)
                for s, e in zip(starts, ends):
                    seg = moved[s:e]
                    red = jnp.max(seg, axis=0) if kind == "max" else jnp.mean(seg, axis=0)
                    slices.append(red)
                out = jnp.moveaxis(jnp.stack(slices, axis=0), 0, ss + d)
        return out

    return dispatch(name, impl, (x,))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", "adaptive_avg_pool2d",
                     spatial_start=2 if data_format.startswith("NC") else 1)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", "adaptive_avg_pool3d",
                     spatial_start=2 if data_format.startswith("NC") else 1)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "max", "adaptive_max_pool3d")
