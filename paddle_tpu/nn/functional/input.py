"""Input/embedding functionals (analogue of python/paddle/nn/functional/input.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["embedding", "one_hot"]

from ...tensor.manipulation import one_hot


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup.  ``sparse`` is accepted for API parity; on TPU the
    lookup is a gather and the gradient a scatter-add — XLA's native sparse
    path (reference: selected-rows grad in
    ``paddle/phi/kernels/selected_rows/embedding_grad_kernel.cc``)."""

    def impl(w, idx):
        # jnp.take's default fill mode returns NaN rows for out-of-range
        # token ids — a mis-tokenized batch fails loudly within one step
        # instead of silently training on a clamped row
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (idx == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return dispatch("embedding", impl, (weight, x), nondiff_mask=[False, True])
