"""Activation functions (analogue of python/paddle/nn/functional/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch
from ...tensor._helpers import normalize_axis

__all__ = [
    "relu", "relu_", "relu6", "elu", "selu", "celu", "gelu", "silu", "swish",
    "sigmoid", "hardsigmoid", "hardswish", "hardtanh", "hardshrink",
    "softshrink", "tanhshrink", "leaky_relu", "prelu", "rrelu", "log_sigmoid",
    "maxout", "softplus", "softsign", "tanh", "mish", "softmax", "softmax_",
    "log_softmax", "gumbel_softmax", "glu", "thresholded_relu",
]


def relu(x, name=None):
    return dispatch("relu", jax.nn.relu, (x,))


def relu_(x, name=None):
    x._in_place_update(relu(x))
    return x


def relu6(x, name=None):
    return dispatch("relu6", jax.nn.relu6, (x,))


def elu(x, alpha=1.0, name=None):
    return dispatch("elu", lambda a: jax.nn.elu(a, alpha), (x,))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return dispatch(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), (x,))


def celu(x, alpha=1.0, name=None):
    return dispatch("celu", lambda a: jax.nn.celu(a, alpha), (x,))


def gelu(x, approximate=False, name=None):
    return dispatch("gelu", lambda a: jax.nn.gelu(a, approximate=approximate),
                    (x,))


def silu(x, name=None):
    return dispatch("silu", jax.nn.silu, (x,))


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return dispatch("sigmoid", jax.nn.sigmoid, (x,))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return dispatch("hardsigmoid",
                    lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), (x,))


def hardswish(x, name=None):
    return dispatch("hardswish",
                    lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, (x,))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return dispatch("hardtanh", lambda a: jnp.clip(a, min, max), (x,))


def hardshrink(x, threshold=0.5, name=None):
    return dispatch(
        "hardshrink",
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype), (x,))


def softshrink(x, threshold=0.5, name=None):
    return dispatch(
        "softshrink",
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)
                            ).astype(a.dtype),
        (x,))


def tanhshrink(x, name=None):
    return dispatch("tanhshrink", lambda a: a - jnp.tanh(a), (x,))


def leaky_relu(x, negative_slope=0.01, name=None):
    return dispatch("leaky_relu",
                    lambda a: jax.nn.leaky_relu(a, negative_slope), (x,))


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        if data_format == "NCHW":
            shape = (1, -1) + (1,) * (a.ndim - 2)
        else:
            shape = (1,) * (a.ndim - 1) + (-1,)
        return jnp.where(a > 0, a, w.reshape(shape) * a)

    return dispatch("prelu", impl, (x, weight))


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ...core.generator import default_generator
    if training:
        key = default_generator().next_key()

        def impl(a):
            r = jax.random.uniform(key, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, r * a)

        return dispatch("rrelu", impl, (x,))
    mid = (lower + upper) / 2.0
    return dispatch("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), (x,))


def log_sigmoid(x, name=None):
    return dispatch("log_sigmoid", jax.nn.log_sigmoid, (x,))


def maxout(x, groups, axis=1, name=None):
    def impl(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (groups, c // groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax)

    return dispatch("maxout", impl, (x,))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return dispatch(
        "softplus",
        lambda a: jnp.where(beta * a > threshold, a,
                            jnp.log1p(jnp.exp(beta * a)) / beta).astype(a.dtype),
        (x,))


def softsign(x, name=None):
    return dispatch("softsign", jax.nn.soft_sign, (x,))


def tanh(x, name=None):
    return dispatch("tanh", jnp.tanh, (x,))


def mish(x, name=None):
    return dispatch("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)), (x,))


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        arr = a.astype(d) if d is not None else a
        return jax.nn.softmax(arr, axis=axis)

    return dispatch("softmax", impl, (x,))


def softmax_(x, axis=-1, dtype=None, name=None):
    x._in_place_update(softmax(x, axis, dtype))
    return x


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtypes import convert_dtype
    d = convert_dtype(dtype)

    def impl(a):
        arr = a.astype(d) if d is not None else a
        return jax.nn.log_softmax(arr, axis=axis)

    return dispatch("log_softmax", impl, (x,))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.generator import default_generator
    key = default_generator().next_key()

    def impl(a):
        g = jax.random.gumbel(key, a.shape, a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis) \
                if hasattr(jnp, "put_along_axis") else \
                y_hard.at[..., :].set(jax.nn.one_hot(
                    jnp.argmax(y, axis=axis), y.shape[axis], dtype=y.dtype))
            y = jax.lax.stop_gradient(y_hard - y) + y
        return y

    return dispatch("gumbel_softmax", impl, (x,))


def glu(x, axis=-1, name=None):
    def impl(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)

    return dispatch("glu", impl, (x,))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return dispatch(
        "thresholded_relu",
        lambda a: jnp.where(a > threshold, a, value).astype(a.dtype), (x,))
