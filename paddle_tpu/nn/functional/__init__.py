"""paddle_tpu.nn.functional — functional neural-net ops.

Analogue of ``python/paddle/nn/functional/``.  Convs/pools lower to
``lax.conv_general_dilated`` / ``lax.reduce_window`` (MXU/VPU native);
attention routes to the Pallas flash-attention kernel on TPU
(:mod:`paddle_tpu.ops.pallas`) with a pure-XLA fallback elsewhere.
"""

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .input import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .decoding import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
