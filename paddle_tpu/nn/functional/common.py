"""Common functional ops: linear, dropout, pad, interpolate, embedding-adjacent
utilities (analogue of python/paddle/nn/functional/common.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch
from ...core.generator import default_generator
from ...core.tensor import Tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "pad",
    "interpolate", "upsample", "bilinear", "cosine_similarity", "pixel_shuffle",
    "pixel_unshuffle", "channel_shuffle", "label_smooth", "unfold", "fold",
    "zeropad2d",
]

from ...tensor.manipulation import pad  # shared impl


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (reference convention)."""
    if bias is None:
        return dispatch("linear", lambda a, w: jnp.matmul(a, w), (x, weight))
    return dispatch("linear",
                    lambda a, w, b: jnp.matmul(a, w) + b, (x, weight, bias))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return dispatch("dropout", lambda a: a * (1.0 - p), (x,))
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = default_generator().next_key()

    def impl(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return dispatch("dropout", impl, (x,))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = default_generator().next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def impl(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return dispatch("alpha_dropout", impl, (x,))


def _resize_nearest(a, out_hw, data_format):
    nhwc = a if data_format == "NHWC" else jnp.transpose(a, (0, 2, 3, 1))
    n, h, w, c = nhwc.shape
    oh, ow = out_hw
    rows = (jnp.arange(oh) * (h / oh)).astype(jnp.int32)
    cols = (jnp.arange(ow) * (w / ow)).astype(jnp.int32)
    out = nhwc[:, rows][:, :, cols]
    return out if data_format == "NHWC" else jnp.transpose(out, (0, 3, 1, 2))


def _resize_linear_nd(a, out_spatial, data_format, align_corners, ndim_spatial):
    # channels-last resize via jax.image
    if data_format.startswith("NC"):
        perm = (0,) + tuple(range(2, 2 + ndim_spatial)) + (1,)
        a = jnp.transpose(a, perm)
    n = a.shape[0]
    c = a.shape[-1]
    method = "bilinear" if ndim_spatial >= 2 else "linear"
    if ndim_spatial == 3:
        method = "trilinear"
    out = jax.image.resize(a, (n,) + tuple(out_spatial) + (c,), method=method)
    if data_format.startswith("NC"):
        inv = (0, ndim_spatial + 1) + tuple(range(1, 1 + ndim_spatial))
        out = jnp.transpose(out, inv)
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def impl(a):
        ndim_spatial = a.ndim - 2
        if data_format.startswith("NC"):
            in_spatial = a.shape[2:]
        else:
            in_spatial = a.shape[1:-1]
        if size is not None:
            out_spatial = tuple(int(s.item()) if isinstance(s, Tensor) else int(s)
                                for s in (size if isinstance(size, (list, tuple))
                                          else [size] * ndim_spatial))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * ndim_spatial
            out_spatial = tuple(int(s * f) for s, f in zip(in_spatial, sf))
        if mode == "nearest" and ndim_spatial == 2:
            return _resize_nearest(a, out_spatial, data_format)
        return _resize_linear_nd(a, out_spatial, data_format, align_corners,
                                 ndim_spatial)

    return dispatch("interpolate", impl, (x,))


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def impl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out

    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return dispatch("bilinear", impl, args)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def impl(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return dispatch("cosine_similarity", impl, (x1, x2))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oc = c // (r * r)
            out = a.reshape(n, oc, r, r, h, w)
            out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
            return out.reshape(n, oc, h * r, w * r)
        n, h, w, c = a.shape
        oc = c // (r * r)
        out = a.reshape(n, h, w, r, r, oc)
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, h * r, w * r, oc)

    return dispatch("pixel_shuffle", impl, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            oh, ow = h // r, w // r
            out = a.reshape(n, c, oh, r, ow, r)
            out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
            return out.reshape(n, c * r * r, oh, ow)
        n, h, w, c = a.shape
        oh, ow = h // r, w // r
        out = a.reshape(n, oh, r, ow, r, c)
        out = jnp.transpose(out, (0, 1, 3, 2, 4, 5))
        return out.reshape(n, oh, ow, c * r * r)

    return dispatch("pixel_unshuffle", impl, (x,))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def impl(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            out = a.reshape(n, groups, c // groups, h, w)
            out = jnp.swapaxes(out, 1, 2)
            return out.reshape(n, c, h, w)
        n, h, w, c = a.shape
        out = a.reshape(n, h, w, groups, c // groups)
        out = jnp.swapaxes(out, 3, 4)
        return out.reshape(n, h, w, c)

    return dispatch("channel_shuffle", impl, (x,))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def impl(lbl, *rest):
        k = lbl.shape[-1]
        if rest:
            return (1.0 - epsilon) * lbl + epsilon * rest[0]
        return (1.0 - epsilon) * lbl + epsilon / k

    args = (label, prior_dist) if prior_dist is not None else (label,)
    return dispatch("label_smooth", impl, args)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def impl(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
        kh, kw = ks
        oh = (a_p.shape[2] - (dl[0] * (kh - 1) + 1)) // st[0] + 1
        ow = (a_p.shape[3] - (dl[1] * (kw - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                di, dj = i * dl[0], j * dl[1]
                patch = a_p[:, :, di:di + oh * st[0]:st[0], dj:dj + ow * st[1]:st[1]]
                patches.append(patch)
        out = jnp.stack(patches, axis=2)  # n, c, kh*kw, oh, ow
        return out.reshape(n, c * kh * kw, oh * ow)

    return dispatch("unfold", impl, (x,))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 4
    if len(pd) == 2:
        pd = [pd[0], pd[0], pd[1], pd[1]]
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def impl(a):
        n, ckk, l = a.shape
        kh, kw = ks
        c = ckk // (kh * kw)
        ph, pw = os_[0] + pd[0] + pd[1], os_[1] + pd[2] + pd[3]
        oh = (ph - (dl[0] * (kh - 1) + 1)) // st[0] + 1
        ow = (pw - (dl[1] * (kw - 1) + 1)) // st[1] + 1
        cols = a.reshape(n, c, kh, kw, oh, ow)
        out = jnp.zeros((n, c, ph, pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                di, dj = i * dl[0], j * dl[1]
                out = out.at[:, :, di:di + oh * st[0]:st[0],
                             dj:dj + ow * st[1]:st[1]].add(cols[:, :, i, j])
        return out[:, :, pd[0]:ph - pd[1], pd[2]:pw - pd[3]]

    return dispatch("fold", impl, (x,))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)
