"""Normalization functionals (analogue of python/paddle/nn/functional/norm.py).

rms_norm / layer_norm route to Pallas fused kernels on TPU when profitable
(:mod:`paddle_tpu.ops.pallas.rms_norm`), else pure-XLA (which fuses well
anyway — the Pallas path exists for the long-row regime).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["normalize", "batch_norm", "layer_norm", "instance_norm",
           "group_norm", "local_response_norm", "rms_norm"]


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)

    return dispatch("normalize", impl, (x,))


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    ns = ((normalized_shape,) if isinstance(normalized_shape, int)
          else tuple(normalized_shape))
    n_axes = len(ns)

    def impl(a, *rest):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon))
        i = 0
        if weight is not None:
            out = out * rest[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(jnp.float32)
        return out.astype(a.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("layer_norm", impl, args)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference: python/paddle/incubate/nn/functional/fused_rms_norm.py)."""
    from ...ops.pallas import rms_norm as pallas_rms
    if weight is not None and pallas_rms.should_use_pallas(x):
        def impl(a, w):
            return pallas_rms.rms_norm(a, w, epsilon)

        return dispatch("rms_norm_pallas", impl, (x, weight))

    def impl(a, *rest):
        acc = a.astype(jnp.float32)
        var = jnp.mean(jnp.square(acc), axis=-1, keepdims=True)
        out = acc * jax.lax.rsqrt(var + epsilon)
        if rest:
            out = out * rest[0].astype(jnp.float32)
        return out.astype(a.dtype)

    args = (x,) + ((weight,) if weight is not None else ())
    return dispatch("rms_norm", impl, args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    """Batch norm.  In training mode the running stats tensors are updated
    in-place (matching the reference's mutable-state semantics)."""
    from ...core.tensor import Tensor

    channels_first = data_format.startswith("NC") and x.ndim > 2
    c_axis = 1 if channels_first or x.ndim == 2 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    use_batch_stats = training and not (use_global_stats is True)

    if use_batch_stats:
        # compute batch stats eagerly so we can update the running buffers.
        # Shifted one-pass moments: sum(x) and sum((x-k)^2) reduce in ONE
        # fused pass over the activation (mean-then-var needs two sequential
        # passes — at conv activation sizes each pass is a full HBM sweep).
        # k is one sample per channel, so the cancellation term
        # (mean - k)^2 is O(var) and fp32 stays accurate even when
        # mean >> std (plain E[x^2]-E[x]^2 catastrophically cancels there).
        # k carries stop_gradient: dvar/dk == 0 analytically, so the grad is
        # exact AND backward avoids a scatter into the sampled positions.
        def stats_impl(a):
            n = a.size // a.shape[c_axis]
            idx = tuple(slice(None) if i == c_axis else slice(0, 1)
                        for i in range(a.ndim))
            # slice the RAW input (a tiny [C] read) — slicing the converted
            # fp32 array would make XLA materialize the whole fp32 copy
            k = jax.lax.stop_gradient(a[idx]).astype(jnp.float32)
            af = a.astype(jnp.float32)
            s = jnp.sum(af, axis=reduce_axes)
            ss = jnp.sum(jnp.square(af - k), axis=reduce_axes)
            m = s / n
            md = m - k.reshape(m.shape)
            v = jnp.maximum(ss / n - md * md, 0.0)
            return m, v

        bmean, bvar = dispatch("batch_norm_stats", stats_impl, (x,))
        if isinstance(running_mean, Tensor):
            running_mean.set_value(momentum * running_mean._value +
                                   (1.0 - momentum) * bmean._value)
            running_var.set_value(momentum * running_var._value +
                                  (1.0 - momentum) * bvar._value)
        mean_t, var_t = bmean, bvar
    else:
        mean_t, var_t = running_mean, running_var

    def impl(a, m, v, *rest):
        shape = [1] * a.ndim
        shape[c_axis] = -1
        af = a.astype(jnp.float32)
        out = (af - m.reshape(shape)) * jax.lax.rsqrt(
            v.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * rest[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (x, mean_t, var_t) + tuple(t for t in (weight, bias)
                                      if t is not None)
    nondiff = [False, True, True] + [False] * (len(args) - 3)
    return dispatch("batch_norm", impl, args, nondiff_mask=nondiff)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    def impl(a, *rest):
        axes = tuple(range(2, a.ndim))
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=axes, keepdims=True)
        v = jnp.var(af, axis=axes, keepdims=True)
        out = (af - m) * jax.lax.rsqrt(v + eps)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * rest[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("instance_norm", impl, args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    channels_first = data_format.startswith("NC")

    def impl(a, *rest):
        if channels_first:
            n, c = a.shape[0], a.shape[1]
            spatial = a.shape[2:]
            g = a.reshape((n, num_groups, c // num_groups) + spatial)
            axes = tuple(range(2, g.ndim))
        else:
            n, c = a.shape[0], a.shape[-1]
            spatial = a.shape[1:-1]
            g = a.reshape((n,) + spatial + (num_groups, c // num_groups))
            axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
        gf = g.astype(jnp.float32)
        m = jnp.mean(gf, axis=axes, keepdims=True)
        v = jnp.var(gf, axis=axes, keepdims=True)
        out = ((gf - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape)
        shape = ([1, -1] + [1] * (a.ndim - 2)) if channels_first \
            else ([1] * (a.ndim - 1) + [-1])
        i = 0
        if weight is not None:
            out = out * rest[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + rest[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return dispatch("group_norm", impl, args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def impl(a):
        sq = jnp.square(a)
        c_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        moved = jnp.moveaxis(sq, c_axis, -1)
        pad = [(0, 0)] * (moved.ndim - 1) + [(size // 2, (size - 1) // 2)]
        padded = jnp.pad(moved, pad)
        window = jnp.stack([padded[..., i:i + moved.shape[-1]]
                            for i in range(size)], axis=0).sum(axis=0)
        div = (k + alpha * window) ** beta
        return a / jnp.moveaxis(div, -1, c_axis)

    return dispatch("local_response_norm", impl, (x,))
