"""Convolutions (analogue of python/paddle/nn/functional/conv.py).

All convs lower to ``lax.conv_general_dilated``, XLA's single conv primitive
that maps onto the MXU (reference equivalent: cuDNN conv kernels in
``paddle/phi/kernels/gpudnn/conv_kernel.cu``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        out = []
        for item in v:
            if isinstance(item, (list, tuple)):
                out.append(tuple(int(i) for i in item))
            else:
                out.append(int(item))
        if len(out) == 1:
            out = out * n
        return out
    return [int(v)] * n


def _conv_padding(padding, n_spatial):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    p = _norm_tuple(padding, n_spatial)
    if all(isinstance(i, int) for i in p):
        if len(p) == n_spatial:
            return [(i, i) for i in p]
        if len(p) == 2 * n_spatial:
            return [(p[2 * i], p[2 * i + 1]) for i in range(n_spatial)]
    return [tuple(i) if isinstance(i, (list, tuple)) else (i, i) for i in p]


def _dim_numbers(n_spatial, data_format):
    sp = "DHW"[3 - n_spatial:]
    if data_format.startswith("NC"):
        lhs = "NC" + sp
    else:
        lhs = "N" + sp + "C"
    rhs = "OI" + sp
    return jax.lax.conv_dimension_numbers(
        (1,) * (n_spatial + 2), (1,) * (n_spatial + 2), (lhs, rhs, lhs))


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format,
          n_spatial, name):
    strides = _norm_tuple(stride, n_spatial)
    dilations = _norm_tuple(dilation, n_spatial)
    pad = _conv_padding(padding, n_spatial)

    def impl(a, w, *rest):
        dn = _dim_numbers(n_spatial, data_format)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=strides, padding=pad,
            rhs_dilation=dilations, dimension_numbers=dn,
            feature_group_count=groups,
            preferred_element_type=None)
        if rest:
            b = rest[0]
            if data_format.startswith("NC"):
                b = b.reshape((1, -1) + (1,) * n_spatial)
            out = out + b
        return out

    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch(name, impl, args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, df, 1,
                 "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 2, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 data_format, 3, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, data_format, n_spatial, output_size, name):
    strides = _norm_tuple(stride, n_spatial)
    dilations = _norm_tuple(dilation, n_spatial)
    pad = _conv_padding(padding, n_spatial)
    opad = _norm_tuple(output_padding, n_spatial)

    def impl(a, w, *rest):
        sp = "DHW"[3 - n_spatial:]
        lhs = ("NC" + sp) if data_format.startswith("NC") else ("N" + sp + "C")
        # weight layout for paddle conv_transpose: [in, out/groups, *k] = IO<sp>
        dn = jax.lax.conv_dimension_numbers(
            a.shape, w.shape, (lhs, "IO" + sp, lhs))
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # transpose conv effective padding: k-1-p on each side (+output_padding)
            ksp = w.shape[2:]
            padding_cfg = []
            for i in range(n_spatial):
                k_eff = dilations[i] * (ksp[i] - 1) + 1
                lo = k_eff - 1 - pad[i][0]
                hi = k_eff - 1 - pad[i][1] + opad[i]
                padding_cfg.append((lo, hi))
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=(1,) * n_spatial, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups,
        )
        if rest:
            b = rest[0]
            if data_format.startswith("NC"):
                b = b.reshape((1, -1) + (1,) * n_spatial)
            out = out + b
        return out

    def impl_flip(a, w, *rest):
        # conv_transpose = conv with flipped spatial kernel & swapped in/out
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n_spatial)))
        return impl(a, wf, *rest)

    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch(name, impl_flip, args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NCH" if data_format == "NCL" else "NHC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, df, 1, output_size,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, 2, output_size,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, data_format, 3, output_size,
                           "conv3d_transpose")
