"""Vision functionals (subset of python/paddle/nn/functional/vision.py)."""

from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["affine_grid", "grid_sample"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def impl(th):
        n, c, h, w = [int(s) for s in out_shape]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) + 0.5) / h * 2 - 1
            xs = (jnp.arange(w) + 0.5) / w * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # H,W,3
        grid = jnp.einsum("hwk,nok->nhwo", base, th)
        return grid

    return dispatch("affine_grid", impl, (theta,))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    def impl(a, g):
        n, c, h, w = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            batch = jnp.arange(n)[:, None, None]
            vals = a[batch, :, iyc, ixc]  # n, gh, gw, c
            if padding_mode == "zeros":
                inside = ((ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1))
                vals = vals * inside[..., None]
            return vals

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
        else:
            x0, y0 = jnp.floor(fx), jnp.floor(fy)
            x1, y1 = x0 + 1, y0 + 1
            wa = (x1 - fx) * (y1 - fy)
            wb = (x1 - fx) * (fy - y0)
            wc = (fx - x0) * (y1 - fy)
            wd = (fx - x0) * (fy - y0)
            out = (sample(x0, y0) * wa[..., None] + sample(x0, y1) * wb[..., None]
                   + sample(x1, y0) * wc[..., None] + sample(x1, y1) * wd[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))

    return dispatch("grid_sample", impl, (x, grid))
