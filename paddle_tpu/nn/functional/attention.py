"""Attention functionals.

Analogue of ``python/paddle/nn/functional/flash_attention.py`` (which calls
the FlashAttention-2 CUDA kernels, reference
``paddle/phi/kernels/gpu/flash_attn_kernel.cu``).  Here the TPU path is a
Pallas flash-attention kernel (:mod:`paddle_tpu.ops.pallas.flash_attention`);
elsewhere a pure-XLA softmax attention (which XLA fuses reasonably well).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sparse_attention", "sdp_kernel"]


def _dropout_key():
    from ...core.generator import default_generator
    return default_generator().next_key()


def _xla_attention(q, k, v, mask=None, causal=False, dropout_p=0.0,
                   dropout_key=None, scale=None):
    # q,k,v: [B, S, H, D] (paddle flash-attn layout)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / (d ** 0.5)
    qt = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # grouped-query attention: broadcast kv heads if fewer
    if kt.shape[1] != qt.shape[1]:
        rep = qt.shape[1] // kt.shape[1]
        kt = jnp.repeat(kt, rep, axis=1)
        vt = jnp.repeat(vt, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * s
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -1e30)
    if mask is not None:
        logits = logits + mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0) \
            .astype(probs.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)  # back to B,S,H,D


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """q/k/v: [batch, seq, heads, head_dim] — reference flash_attention API."""
    from ...ops.pallas import flash_attention as pallas_fa
    if pallas_fa.should_use_pallas(query, causal=causal,
                                   dropout=dropout if training else 0.0,
                                   key=key):
        def impl(q, k, v):
            return pallas_fa.flash_attention(q, k, v, causal=causal)

        out = dispatch("flash_attention", impl, (query, key, value))
        return (out, None) if return_softmax else out

    p = dropout if training else 0.0
    dkey = _dropout_key() if p > 0.0 else None

    def impl(q, k, v):
        return _xla_attention(q, k, v, causal=causal, dropout_p=p,
                              dropout_key=dkey)

    out = dispatch("flash_attention", impl, (query, key, value))
    if return_softmax:
        return out, None
    return out


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """q/k/v: [batch, seq, heads, head_dim] (reference API layout)."""
    from ...ops.pallas import flash_attention as pallas_fa
    if attn_mask is None and pallas_fa.should_use_pallas(
            query, causal=is_causal,
            dropout=dropout_p if training else 0.0, key=key):
        def impl(q, k, v):
            return pallas_fa.flash_attention(q, k, v, causal=is_causal)

        return dispatch("flash_attention", impl, (query, key, value))

    p = dropout_p if training else 0.0
    dkey = _dropout_key() if p > 0.0 else None

    if attn_mask is None:
        def impl(q, k, v):
            return _xla_attention(q, k, v, causal=is_causal, dropout_p=p,
                                  dropout_key=dkey)

        return dispatch("sdpa", impl, (query, key, value))

    def impl(q, k, v, m):
        return _xla_attention(q, k, v, mask=m, causal=is_causal, dropout_p=p,
                              dropout_key=dkey)

    return dispatch("sdpa", impl, (query, key, value, attn_mask),
                    nondiff_mask=[False, False, False, True])


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Varlen attention: runs dense attention with a mask built from the
    cumulative sequence lengths (XLA wants static shapes; the padded-dense
    form is the TPU-native expression of varlen batches)."""

    def impl(q, k, v, cu_q, cu_k):
        # q: [total_q, H, D] packed; reconstruct per-seq mask on the fly
        b = cu_q.shape[0] - 1
        # build dense [B, max_q, H, D]
        def gather_seq(packed, cu, max_len):
            def one(i):
                start = cu[i]
                length = cu[i + 1] - start
                idx = start + jnp.minimum(jnp.arange(max_len), length - 1)
                seq = jnp.take(packed, idx, axis=0)
                valid = (jnp.arange(max_len) < length)[:, None, None]
                return seq * valid
            return jax.vmap(one)(jnp.arange(b))

        qd = gather_seq(q, cu_q, max_seqlen_q)
        kd = gather_seq(k, cu_k, max_seqlen_k)
        vd = gather_seq(v, cu_k, max_seqlen_k)
        lens_q = cu_q[1:] - cu_q[:-1]
        lens_k = cu_k[1:] - cu_k[:-1]
        mask = jnp.where(
            (jnp.arange(max_seqlen_k)[None, None, None, :] <
             lens_k[:, None, None, None]), 0.0, -1e30)
        out = _xla_attention(qd, kd, vd, mask=mask, causal=causal, scale=scale)
        # pack back to [total_q, H, D]
        total_q = q.shape[0]
        flat = out.reshape(-1, out.shape[-2], out.shape[-1])
        pos = (cu_q[:-1, None] +
               jnp.arange(max_seqlen_q)[None, :]).reshape(-1)
        valid = (jnp.arange(max_seqlen_q)[None, :] <
                 (cu_q[1:] - cu_q[:-1])[:, None]).reshape(-1)
        res = jnp.zeros_like(q)
        res = res.at[jnp.where(valid, pos, total_q - 1)].add(
            flat * valid[:, None, None])
        return res

    return dispatch("flash_attn_unpadded", impl,
                    (query, key, value, cu_seqlens_q, cu_seqlens_k),
                    nondiff_mask=[False, False, False, True, True])


class sdp_kernel:
    """Context selecting attention backends (API parity shim)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        self.enable_flash = enable_flash

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Attention restricted to a CSR sparsity pattern (reference
    ``python/paddle/nn/functional/sparse_attention.py`` over the
    ``sparse_attention`` CUDA kernel, CUDA>=11.3 only there).

    q/k/v: [B, H, S, D]; ``sparse_csr_offset`` [B, H, S+1] int32 row
    offsets; ``sparse_csr_columns`` [B, H, nnz] int32 column indices.
    ``key_padding_mask`` [B, S] / ``attn_mask`` [S, S]: 0 means masked
    (the reference's convention).

    TPU formulation: the CSR pattern is a LAYOUT descriptor, not a
    compute schedule — the pattern is scattered into a dense boolean
    mask once and the attention itself runs as dense masked QK^T /
    softmax / AV on the MXU (block-sparse skipping only pays off when
    whole 128-wide tiles drop; at that point use the Pallas flash kernel
    with a block mask).  Results match the reference kernel at the
    stored positions; softmax is over each row's stored columns only.
    """

    def impl(q, k, v, offset, cols, kp, am):
        b, h, s, d = q.shape
        nnz = cols.shape[-1]
        idx = jnp.arange(nnz)
        # row of each nnz slot = #(row starts <= slot): offset[..., 1:]
        # is [B, H, S]; compare against slot ids -> [B, H, S, nnz]
        rows = (idx[None, None, None, :]
                >= offset[..., 1:, None]).sum(axis=-2)       # [B, H, nnz]
        valid = idx[None, None, :] < offset[..., -1:]        # [B, H, nnz]
        bidx = jnp.arange(b)[:, None, None]
        hidx = jnp.arange(h)[None, :, None]
        mask = jnp.zeros((b, h, s, s), bool)
        mask = mask.at[bidx, hidx, rows,
                       jnp.clip(cols, 0, s - 1)].max(valid)
        scores = jnp.einsum("bhsd,bhtd->bhst", q, k,
                            preferred_element_type=jnp.float32)
        scores = scores / jnp.sqrt(jnp.float32(d))
        if kp is not None:  # [B, S], 0 = masked key position
            mask = mask & (kp[:, None, None, :] != 0)
        if am is not None:  # [S, S], 0 = masked pair
            mask = mask & (am[None, None, :, :] != 0)
        neg = jnp.float32(-1e30)
        scores = jnp.where(mask, scores, neg)
        probs = jax.nn.softmax(scores, axis=-1)
        probs = jnp.where(mask, probs, 0.0)  # fully-masked rows -> 0
        return jnp.einsum("bhst,bhtd->bhsd",
                          probs.astype(q.dtype), v)

    from ...core.tensor import Tensor as _T

    def _opt(x):
        return None if x is None else (
            x._value if isinstance(x, _T) else jnp.asarray(x))

    kp, am = _opt(key_padding_mask), _opt(attn_mask)
    return dispatch(
        "sparse_attention",
        lambda q, k, v, o, c: impl(q, k, v, o, c, kp, am),
        (query, key, value, sparse_csr_offset, sparse_csr_columns),
        nondiff_mask=[False, False, False, True, True])
