"""Decoding functionals: ``gather_tree``.

Reference parity: ``paddle.nn.functional.gather_tree`` (CUDA kernel
``paddle/phi/kernels/gpu/gather_tree_kernel.cu`` — per-(batch, beam)
thread chasing parent pointers backward through time).  TPU formulation:
a REVERSE ``lax.scan`` over the time axis carrying the current parent
index per (batch, beam); each step is one batched gather — vectorized,
static-shape, jit-friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import dispatch

__all__ = ["gather_tree"]


def _gather_tree_arrays(idv, parv):
    """The reverse-scan backtrace on raw [T, B, K] arrays — the single
    implementation behind ``gather_tree`` and the compiled beam paths
    (models/generation.py, inference/llm.py)."""
    t, b, k = idv.shape
    binds = jnp.arange(b)[:, None]

    def body(parent, xs):
        id_t, par_t = xs                       # [B, K] each
        tok = id_t[binds, parent]
        return par_t[binds, parent], tok

    init = jnp.tile(jnp.arange(k, dtype=parv.dtype)[None], (b, 1))
    _, toks = jax.lax.scan(body, init, (idv, parv), reverse=True)
    return toks


def gather_tree(ids, parents):
    """Backtrace beam-search output ``ids [T, B, K]`` along
    ``parents [T, B, K]`` into beam-consistent full sequences
    ``[T, B, K]``: the k-th output sequence is the actual token path
    ending at beam k of the last step."""

    def impl(idv, parv):
        if idv.ndim != 3 or idv.shape != parv.shape:
            raise ValueError(
                f"gather_tree expects ids and parents of equal shape "
                f"[T, B, K], got {idv.shape} vs {parv.shape}")
        return _gather_tree_arrays(idv, parv)

    return dispatch("gather_tree", impl, (ids, parents))
