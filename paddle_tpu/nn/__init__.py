"""paddle_tpu.nn — neural network layers (analogue of paddle.nn)."""

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from .lazy import LazyGuard, in_lazy_mode  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm,  # noqa: F401
                   ClipGradByValue, clip_grad_norm_, clip_grad_value_)
from .layer.activation import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.layers import Layer, ParamAttr, Parameter  # noqa: F401
from .layer.loss import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
