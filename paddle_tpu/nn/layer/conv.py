"""Conv layers (analogue of python/paddle/nn/layer/conv.py)."""

from __future__ import annotations

import numpy as np

from .. import functional as F
from ..initializer import KaimingUniform
from .layers import Layer

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose"]


def _tup(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n_spatial,
                 stride=1, padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _tup(kernel_size, n_spatial)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self.output_padding = output_padding
        self._n_spatial = n_spatial
        if transpose:
            wshape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={list(self.kernel_size)}, stride={self.stride}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)
